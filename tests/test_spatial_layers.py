"""Distributed-vs-sequential layer equivalence tests.

TPU rebuild of the reference's conv validation benchmarks
(``benchmark_sp_halo_exchange_with_compute_val.py:704-780``,
``benchmark_sp_halo_exchange_conv.py:940-1092``): a spatially-partitioned
conv/pool over the tile mesh must produce exactly the tiles of the
single-device ("sequential") op on the full image. Unlike the reference we
don't need to force weights to 1.0 — CPU simulation is deterministic — but we
keep one ones-weight case for parity with the reference harness.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from mpi4dl_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.config import tile_grid
from mpi4dl_tpu.ops.layers import Conv2d, Pool

SPEC = P(None, "tile_h", "tile_w", None)


def _mesh(th, tw):
    dev = np.asarray(jax.devices()[: th * tw]).reshape(th, tw)
    return Mesh(dev, ("tile_h", "tile_w"))


def _run_distributed(module_spatial, module_plain, x, mesh, params=None):
    """Init plain module single-device, run spatial module under shard_map
    with the same params, return (distributed_out, golden_out)."""
    key = jax.random.PRNGKey(0)
    if params is None:
        params = module_plain.init(key, x)
    golden = module_plain.apply(params, x)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), SPEC),
        out_specs=SPEC,
        check_vma=False,
    )
    def dist_apply(p, tile):
        return module_spatial.apply(p, tile)

    xs = jax.device_put(x, NamedSharding(mesh, SPEC))
    out = dist_apply(params, xs)
    return np.asarray(out), np.asarray(golden)


@pytest.mark.parametrize("slice_method,parts", [("square", 4), ("vertical", 4), ("horizontal", 4)])
@pytest.mark.parametrize("kernel,stride", [(3, 1), (3, 2), (1, 1), (5, 1)])
def test_spatial_conv_matches_sequential(slice_method, parts, kernel, stride):
    th, tw = tile_grid(parts, slice_method)
    mesh = _mesh(th, tw)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), dtype=jnp.float32)

    plain = Conv2d(features=8, kernel_size=kernel, strides=stride, spatial=False)
    spatial = Conv2d(features=8, kernel_size=kernel, strides=stride, spatial=True)
    out, golden = _run_distributed(spatial, plain, x, mesh)
    np.testing.assert_allclose(out, golden, rtol=1e-5, atol=1e-5)


def test_spatial_conv_ones_weights_integer_exact():
    """Reference-parity case: weights/bias forced to 1.0 on an arange image
    (``benchmark_sp_halo_exchange_with_compute_val.py:704-706``)."""
    mesh = _mesh(2, 2)
    x = jnp.arange(1 * 8 * 8 * 2, dtype=jnp.float32).reshape(1, 8, 8, 2)
    plain = Conv2d(features=4, kernel_size=3, spatial=False)
    spatial = Conv2d(features=4, kernel_size=3, spatial=True)
    params = plain.init(jax.random.PRNGKey(0), x)
    params = jax.tree.map(lambda a: jnp.ones_like(a), params)
    out, golden = _run_distributed(spatial, plain, x, mesh, params=params)
    np.testing.assert_array_equal(out, golden)


@pytest.mark.parametrize("kind", ["max", "avg"])
@pytest.mark.parametrize("kernel,stride,padding", [(2, 2, 0), (3, 2, 1), (3, 1, 1)])
def test_spatial_pool_matches_sequential(kind, kernel, stride, padding):
    mesh = _mesh(2, 2)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), dtype=jnp.float32)
    plain = Pool(kind=kind, kernel_size=kernel, strides=stride, padding=padding)
    spatial = Pool(
        kind=kind, kernel_size=kernel, strides=stride, padding=padding, spatial=True
    )
    out, golden = _run_distributed(spatial, plain, x, mesh)
    np.testing.assert_allclose(out, golden, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize(
    "kernel,stride,padding,shape",
    [
        ((3, 3), (2, 2), (1, 1), (2, 16, 16, 3)),
        ((2, 2), (2, 2), (0, 0), (2, 16, 16, 3)),
        ((3, 3), (2, 2), (1, 1), (1, 15, 17, 5)),  # odd extents
        ((3, 2), (2, 3), (1, 0), (2, 12, 18, 4)),  # rectangular
    ],
)
def test_max_pool_strided_backward_matches_select_and_scatter(
    kernel, stride, padding, shape
):
    """The decomposed strided-pool backward (ops/layers.py
    ``max_pool_strided``) claims BIT-IDENTICAL semantics to XLA's
    ``select_and_scatter`` (first max in row-major window order wins the
    gradient). Proven here on tie-HEAVY data — small integers, so most
    windows contain duplicated maxima and any tie-breaking difference
    shows up immediately."""
    from mpi4dl_tpu.ops.layers import max_pool_strided

    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    rng = np.random.default_rng(7)
    # Integer values 0..3: ties everywhere.
    x = jnp.asarray(rng.integers(0, 4, size=shape), jnp.float32)

    def via_decomposed(x):
        y = max_pool_strided(x, kh, kw, sh, sw, ph, pw)
        return jnp.sum(y * jnp.cos(jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape))

    def via_xla(x):
        import flax.linen as nn

        y = nn.max_pool(
            x, (kh, kw), strides=(sh, sw), padding=((ph, ph), (pw, pw))
        )
        return jnp.sum(y * jnp.cos(jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape))

    v1, g1 = jax.value_and_grad(via_decomposed)(x)
    v2, g2 = jax.value_and_grad(via_xla)(x)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    # Gradient ROUTING must be identical; the only tolerated difference is
    # f32 summation order where several windows hit one input element
    # (~1e-7). A tie-breaking divergence would misroute whole dy values
    # (magnitude ~1) and fail this bound by 6 orders.
    np.testing.assert_allclose(
        np.asarray(g1), np.asarray(g2), rtol=1e-6, atol=1e-6
    )


@pytest.mark.parametrize("spatial", [False, True])
def test_pool_decomposed_backward_dispatch(spatial, monkeypatch):
    """MPI4DL_TPU_POOL_BWD=decomposed through the Pool MODULE (the pad
    plumbing and the spatial halo-exchange + trim composition, which the
    direct max_pool_strided parity test bypasses): value AND input
    gradient must match the default-impl Pool exactly."""
    from mpi4dl_tpu.ops.layers import Pool

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 4, size=(2, 16, 16, 3)), jnp.float32)
    pool_kw = dict(kind="max", kernel_size=3, strides=2, padding=1)
    mesh = _mesh(2, 2) if spatial else None

    def run(impl):
        monkeypatch.setenv("MPI4DL_TPU_POOL_BWD", impl)
        plain = Pool(**pool_kw)
        params = plain.init(jax.random.PRNGKey(0), x)
        if not spatial:
            def loss(x):
                y = plain.apply(params, x)
                return jnp.sum(y * jnp.cos(
                    jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape))

            return jax.value_and_grad(loss)(x)

        sp = Pool(**pool_kw, spatial=True)

        @jax.jit
        def loss(x):
            from mpi4dl_tpu.compat import shard_map
            from jax.sharding import PartitionSpec

            def local(xt):
                y = sp.apply(params, xt)
                # Position-dependent weights: a mis-padded/mis-trimmed
                # backward would route gradient to the wrong inputs and
                # diverge from the default impl immediately.
                w = jnp.cos(jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape)
                return jax.lax.psum(jnp.sum(y * w), ("tile_h", "tile_w"))

            f = shard_map(
                local, mesh=mesh,
                in_specs=SPEC, out_specs=PartitionSpec(),
                check_vma=False,
            )
            return f(x)

        return jax.value_and_grad(loss)(x)

    v_dec, g_dec = run("decomposed")
    v_xla, g_xla = run("xla")
    np.testing.assert_array_equal(np.asarray(v_dec), np.asarray(v_xla))
    np.testing.assert_allclose(
        np.asarray(g_dec), np.asarray(g_xla), rtol=1e-6, atol=1e-6
    )


def test_bn_fused_backward_matches_stock_ad(monkeypatch):
    """The MPI4DL_TPU_BN_BWD=fused lever's hand-derived backward
    (``dx = x·(2·ct_sq/n) + ct_mean/n``) must equal stock AD — checked
    through a full TrainBatchNorm apply (scale/bias gradients included),
    which is how every model reaches bn_moments."""
    from mpi4dl_tpu.ops.layers import TrainBatchNorm

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 5)), jnp.float32)
    bn = TrainBatchNorm()
    params = bn.init(jax.random.PRNGKey(0), x)

    def grads(impl):
        monkeypatch.setenv("MPI4DL_TPU_BN_BWD", impl)

        def loss(params, x):
            y = bn.apply(params, x)
            w = jnp.cos(jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape)
            return jnp.sum(y * w)

        (v, gx), gp = (
            jax.value_and_grad(loss, argnums=1)(params, x),
            jax.grad(loss, argnums=0)(params, x),
        )
        return v, gx, gp

    v_f, gx_f, gp_f = grads("fused")
    v_x, gx_x, gp_x = grads("xla")
    np.testing.assert_allclose(float(v_f), float(v_x), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(gx_f), np.asarray(gx_x), rtol=1e-5, atol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        gp_f,
        gp_x,
    )


def test_spatial_window_coverage_check():
    """Spatial windowed ops whose halo can't cover cross-boundary windows
    must fail loudly instead of silently dropping windows."""
    mesh = _mesh(2, 2)
    x = jnp.zeros((1, 8, 8, 2), jnp.float32)
    for mod in (
        Conv2d(features=2, kernel_size=3, padding=0, spatial=True),
        Pool(kind="max", kernel_size=3, strides=2, padding=0, spatial=True),
    ):
        with pytest.raises(ValueError, match="cover tile-boundary windows"):
            fn = shard_map(
                lambda t, m=mod: m.apply({"params": {}}, t),
                mesh=mesh,
                in_specs=(SPEC,),
                out_specs=SPEC,
                check_vma=False,
            )
            jax.eval_shape(fn, jax.ShapeDtypeStruct(x.shape, x.dtype))


# -- decomposed halo/compute-overlap impl (ISSUE 9 tentpole) ------------------
# MPI4DL_TPU_CONV_OVERLAP=decomposed splits each spatial windowed op into
# an interior op (no halo dependency — overlappable with the ppermutes)
# plus boundary-strip ops on the exchanged tile (layers.overlap_decompose).
# The contract these tests pin: the stitched output is BIT-IDENTICAL to
# the monolithic exchange form on the CPU mesh (every output window sees
# exactly the same bytes and XLA's per-window reduction order does not
# change with the outer slicing here), so flipping the flag is a pure
# scheduling A/B, never a numerics A/B.


def _strip_bounds_ref(n, k, s, p):
    """Brute-force reference: which trimmed output rows have windows that
    stay inside the local tile."""
    n_out = n // s
    lo = sum(1 for i in range(n_out) if i * s - p < 0)
    hi = sum(1 for i in range(n_out) if i * s - p + k - 1 > n - 1)
    return lo, hi, n_out


@pytest.mark.parametrize(
    "n,k,s,p",
    [(8, 3, 1, 1), (8, 3, 2, 1), (8, 5, 1, 2), (16, 3, 2, 1),
     (4, 3, 1, 1), (2, 3, 1, 1), (8, 1, 1, 0), (8, 2, 2, 0)],
)
def test_strip_bounds_match_bruteforce(n, k, s, p):
    from mpi4dl_tpu.ops.layers import _strip_bounds

    assert _strip_bounds(n, k, s, p) == _strip_bounds_ref(n, k, s, p)


@pytest.mark.parametrize("th,tw", [(2, 2), (1, 4)])
@pytest.mark.parametrize("kernel,stride", [(3, 1), (3, 2), (5, 1)])
def test_decomposed_conv_bit_identical_to_monolithic(th, tw, kernel, stride):
    """Tier-1 equivalence (ISSUE satellite): interior+boundary stitching
    equals the monolithic halo_exchange+conv path bit-for-bit on the CPU
    mesh — square AND vertical grids, stride>1, global-boundary tiles
    (every tile of these grids touches the image boundary)."""
    mesh = _mesh(th, tw)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), dtype=jnp.float32)
    plain = Conv2d(features=8, kernel_size=kernel, strides=stride,
                   spatial=False)
    mono = Conv2d(features=8, kernel_size=kernel, strides=stride,
                  spatial=True, overlap="monolithic")
    dec = Conv2d(features=8, kernel_size=kernel, strides=stride,
                 spatial=True, overlap="decomposed")
    params = plain.init(jax.random.PRNGKey(0), x)
    out_m, golden = _run_distributed(mono, plain, x, mesh, params=params)
    out_d, _ = _run_distributed(dec, plain, x, mesh, params=params)
    np.testing.assert_array_equal(out_d, out_m)
    # And both equal the single-device golden (documented f32 tolerance —
    # the tiled conv may legally differ from the full-image one in
    # accumulation order, decomposed or not).
    np.testing.assert_allclose(out_d, golden, rtol=1e-5, atol=1e-5)


def test_decomposed_conv_env_selected_and_ones_exact(monkeypatch):
    """MPI4DL_TPU_CONV_OVERLAP=decomposed (the process-wide selector,
    overlap=None) on the reference-parity ones-weight integer case:
    exact integer equality against the plain golden."""
    monkeypatch.setenv("MPI4DL_TPU_CONV_OVERLAP", "decomposed")
    mesh = _mesh(2, 2)
    x = jnp.arange(1 * 8 * 8 * 2, dtype=jnp.float32).reshape(1, 8, 8, 2)
    plain = Conv2d(features=4, kernel_size=3, spatial=False)
    spatial = Conv2d(features=4, kernel_size=3, spatial=True)
    params = plain.init(jax.random.PRNGKey(0), x)
    params = jax.tree.map(lambda a: jnp.ones_like(a), params)
    out, golden = _run_distributed(spatial, plain, x, mesh, params=params)
    np.testing.assert_array_equal(out, golden)


def test_decomposed_conv_small_tile_falls_back_to_monolithic():
    """A tile too small for a non-empty interior (here 4x4 under a 5x5
    kernel: every output row needs the halo) must fall back to the
    monolithic path, not emit a degenerate stitch."""
    mesh = _mesh(2, 2)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((1, 8, 8, 2)), dtype=jnp.float32)
    plain = Conv2d(features=4, kernel_size=5, spatial=False)
    dec = Conv2d(features=4, kernel_size=5, spatial=True,
                 overlap="decomposed")
    out, golden = _run_distributed(dec, plain, x, mesh)
    np.testing.assert_allclose(out, golden, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "kind,kernel,stride,padding",
    [("max", 3, 2, 1), ("max", 3, 1, 1), ("avg", 3, 2, 1)],
)
def test_decomposed_pool_bit_identical_to_monolithic(
    kind, kernel, stride, padding
):
    """Pooling variant of the decomposition, on ALL-NEGATIVE data so the
    -inf boundary fill is load-bearing at the global-boundary tiles (a
    zero-fill bug would win every boundary max)."""
    mesh = _mesh(2, 2)
    rng = np.random.default_rng(7)
    x = jnp.asarray(
        -np.abs(rng.standard_normal((2, 16, 16, 3))) - 1.0, jnp.float32
    )
    plain = Pool(kind=kind, kernel_size=kernel, strides=stride,
                 padding=padding)
    mono = Pool(kind=kind, kernel_size=kernel, strides=stride,
                padding=padding, spatial=True, overlap="monolithic")
    dec = Pool(kind=kind, kernel_size=kernel, strides=stride,
               padding=padding, spatial=True, overlap="decomposed")
    out_m, golden = _run_distributed(mono, plain, x, mesh)
    out_d, _ = _run_distributed(dec, plain, x, mesh)
    np.testing.assert_array_equal(out_d, out_m)
    np.testing.assert_allclose(out_d, golden, rtol=1e-6, atol=1e-6)


def test_decomposed_conv_gradients_match_monolithic():
    """The decomposition must be transparent to AD: parameter and input
    gradients through the stitched form match the monolithic form (the
    train step consumes this path, not just the forward)."""
    import functools as _ft

    mesh = _mesh(2, 2)
    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 3)), dtype=jnp.float32)
    plain = Conv2d(features=4, kernel_size=3, strides=1, spatial=False)
    params = plain.init(jax.random.PRNGKey(0), x)

    def loss_fn(mod):
        @jax.jit
        @_ft.partial(
            shard_map, mesh=mesh, in_specs=(P(), SPEC), out_specs=(P(), SPEC),
            check_vma=False,
        )
        def run(p, t):
            def local(p, t):
                return jnp.sum(jnp.square(mod.apply(p, t)))

            (l, gp), gt = (
                jax.value_and_grad(local)(p, t),
                jax.grad(local, argnums=1)(p, t),
            )
            import jax.lax as _lax

            l = _lax.psum(l, ("tile_h", "tile_w"))
            gp = jax.tree.map(
                lambda a: _lax.psum(a, ("tile_h", "tile_w")), gp
            )
            return (l, gp), gt

        xs = jax.device_put(x, NamedSharding(mesh, SPEC))
        (l, gp), gt = run(params, xs)
        return float(l), gp, np.asarray(gt)

    from jax.sharding import NamedSharding

    l_m, gp_m, gt_m = loss_fn(
        Conv2d(features=4, kernel_size=3, strides=1, spatial=True,
               overlap="monolithic")
    )
    l_d, gp_d, gt_d = loss_fn(
        Conv2d(features=4, kernel_size=3, strides=1, spatial=True,
               overlap="decomposed")
    )
    np.testing.assert_allclose(l_d, l_m, rtol=1e-6)
    # Input gradients: the stitch's transpose accumulates halo-overlap
    # contributions (slice-transpose scatter-adds) in a different order
    # than the monolithic conv transpose — documented f32 tolerance, not
    # bit equality (the FORWARD is bit-identical; see the tests above).
    np.testing.assert_allclose(gt_d, gt_m, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        gp_d, gp_m,
    )
