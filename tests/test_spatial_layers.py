"""Distributed-vs-sequential layer equivalence tests.

TPU rebuild of the reference's conv validation benchmarks
(``benchmark_sp_halo_exchange_with_compute_val.py:704-780``,
``benchmark_sp_halo_exchange_conv.py:940-1092``): a spatially-partitioned
conv/pool over the tile mesh must produce exactly the tiles of the
single-device ("sequential") op on the full image. Unlike the reference we
don't need to force weights to 1.0 — CPU simulation is deterministic — but we
keep one ones-weight case for parity with the reference harness.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from mpi4dl_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.config import tile_grid
from mpi4dl_tpu.ops.layers import Conv2d, Pool

SPEC = P(None, "tile_h", "tile_w", None)


def _mesh(th, tw):
    dev = np.asarray(jax.devices()[: th * tw]).reshape(th, tw)
    return Mesh(dev, ("tile_h", "tile_w"))


def _run_distributed(module_spatial, module_plain, x, mesh, params=None):
    """Init plain module single-device, run spatial module under shard_map
    with the same params, return (distributed_out, golden_out)."""
    key = jax.random.PRNGKey(0)
    if params is None:
        params = module_plain.init(key, x)
    golden = module_plain.apply(params, x)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), SPEC),
        out_specs=SPEC,
        check_vma=False,
    )
    def dist_apply(p, tile):
        return module_spatial.apply(p, tile)

    xs = jax.device_put(x, NamedSharding(mesh, SPEC))
    out = dist_apply(params, xs)
    return np.asarray(out), np.asarray(golden)


@pytest.mark.parametrize("slice_method,parts", [("square", 4), ("vertical", 4), ("horizontal", 4)])
@pytest.mark.parametrize("kernel,stride", [(3, 1), (3, 2), (1, 1), (5, 1)])
def test_spatial_conv_matches_sequential(slice_method, parts, kernel, stride):
    th, tw = tile_grid(parts, slice_method)
    mesh = _mesh(th, tw)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), dtype=jnp.float32)

    plain = Conv2d(features=8, kernel_size=kernel, strides=stride, spatial=False)
    spatial = Conv2d(features=8, kernel_size=kernel, strides=stride, spatial=True)
    out, golden = _run_distributed(spatial, plain, x, mesh)
    np.testing.assert_allclose(out, golden, rtol=1e-5, atol=1e-5)


def test_spatial_conv_ones_weights_integer_exact():
    """Reference-parity case: weights/bias forced to 1.0 on an arange image
    (``benchmark_sp_halo_exchange_with_compute_val.py:704-706``)."""
    mesh = _mesh(2, 2)
    x = jnp.arange(1 * 8 * 8 * 2, dtype=jnp.float32).reshape(1, 8, 8, 2)
    plain = Conv2d(features=4, kernel_size=3, spatial=False)
    spatial = Conv2d(features=4, kernel_size=3, spatial=True)
    params = plain.init(jax.random.PRNGKey(0), x)
    params = jax.tree.map(lambda a: jnp.ones_like(a), params)
    out, golden = _run_distributed(spatial, plain, x, mesh, params=params)
    np.testing.assert_array_equal(out, golden)


@pytest.mark.parametrize("kind", ["max", "avg"])
@pytest.mark.parametrize("kernel,stride,padding", [(2, 2, 0), (3, 2, 1), (3, 1, 1)])
def test_spatial_pool_matches_sequential(kind, kernel, stride, padding):
    mesh = _mesh(2, 2)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), dtype=jnp.float32)
    plain = Pool(kind=kind, kernel_size=kernel, strides=stride, padding=padding)
    spatial = Pool(
        kind=kind, kernel_size=kernel, strides=stride, padding=padding, spatial=True
    )
    out, golden = _run_distributed(spatial, plain, x, mesh)
    np.testing.assert_allclose(out, golden, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize(
    "kernel,stride,padding,shape",
    [
        ((3, 3), (2, 2), (1, 1), (2, 16, 16, 3)),
        ((2, 2), (2, 2), (0, 0), (2, 16, 16, 3)),
        ((3, 3), (2, 2), (1, 1), (1, 15, 17, 5)),  # odd extents
        ((3, 2), (2, 3), (1, 0), (2, 12, 18, 4)),  # rectangular
    ],
)
def test_max_pool_strided_backward_matches_select_and_scatter(
    kernel, stride, padding, shape
):
    """The decomposed strided-pool backward (ops/layers.py
    ``max_pool_strided``) claims BIT-IDENTICAL semantics to XLA's
    ``select_and_scatter`` (first max in row-major window order wins the
    gradient). Proven here on tie-HEAVY data — small integers, so most
    windows contain duplicated maxima and any tie-breaking difference
    shows up immediately."""
    from mpi4dl_tpu.ops.layers import max_pool_strided

    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    rng = np.random.default_rng(7)
    # Integer values 0..3: ties everywhere.
    x = jnp.asarray(rng.integers(0, 4, size=shape), jnp.float32)

    def via_decomposed(x):
        y = max_pool_strided(x, kh, kw, sh, sw, ph, pw)
        return jnp.sum(y * jnp.cos(jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape))

    def via_xla(x):
        import flax.linen as nn

        y = nn.max_pool(
            x, (kh, kw), strides=(sh, sw), padding=((ph, ph), (pw, pw))
        )
        return jnp.sum(y * jnp.cos(jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape))

    v1, g1 = jax.value_and_grad(via_decomposed)(x)
    v2, g2 = jax.value_and_grad(via_xla)(x)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    # Gradient ROUTING must be identical; the only tolerated difference is
    # f32 summation order where several windows hit one input element
    # (~1e-7). A tie-breaking divergence would misroute whole dy values
    # (magnitude ~1) and fail this bound by 6 orders.
    np.testing.assert_allclose(
        np.asarray(g1), np.asarray(g2), rtol=1e-6, atol=1e-6
    )


@pytest.mark.parametrize("spatial", [False, True])
def test_pool_decomposed_backward_dispatch(spatial, monkeypatch):
    """MPI4DL_TPU_POOL_BWD=decomposed through the Pool MODULE (the pad
    plumbing and the spatial halo-exchange + trim composition, which the
    direct max_pool_strided parity test bypasses): value AND input
    gradient must match the default-impl Pool exactly."""
    from mpi4dl_tpu.ops.layers import Pool

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 4, size=(2, 16, 16, 3)), jnp.float32)
    pool_kw = dict(kind="max", kernel_size=3, strides=2, padding=1)
    mesh = _mesh(2, 2) if spatial else None

    def run(impl):
        monkeypatch.setenv("MPI4DL_TPU_POOL_BWD", impl)
        plain = Pool(**pool_kw)
        params = plain.init(jax.random.PRNGKey(0), x)
        if not spatial:
            def loss(x):
                y = plain.apply(params, x)
                return jnp.sum(y * jnp.cos(
                    jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape))

            return jax.value_and_grad(loss)(x)

        sp = Pool(**pool_kw, spatial=True)

        @jax.jit
        def loss(x):
            from mpi4dl_tpu.compat import shard_map
            from jax.sharding import PartitionSpec

            def local(xt):
                y = sp.apply(params, xt)
                # Position-dependent weights: a mis-padded/mis-trimmed
                # backward would route gradient to the wrong inputs and
                # diverge from the default impl immediately.
                w = jnp.cos(jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape)
                return jax.lax.psum(jnp.sum(y * w), ("tile_h", "tile_w"))

            f = shard_map(
                local, mesh=mesh,
                in_specs=SPEC, out_specs=PartitionSpec(),
                check_vma=False,
            )
            return f(x)

        return jax.value_and_grad(loss)(x)

    v_dec, g_dec = run("decomposed")
    v_xla, g_xla = run("xla")
    np.testing.assert_array_equal(np.asarray(v_dec), np.asarray(v_xla))
    np.testing.assert_allclose(
        np.asarray(g_dec), np.asarray(g_xla), rtol=1e-6, atol=1e-6
    )


def test_bn_fused_backward_matches_stock_ad(monkeypatch):
    """The MPI4DL_TPU_BN_BWD=fused lever's hand-derived backward
    (``dx = x·(2·ct_sq/n) + ct_mean/n``) must equal stock AD — checked
    through a full TrainBatchNorm apply (scale/bias gradients included),
    which is how every model reaches bn_moments."""
    from mpi4dl_tpu.ops.layers import TrainBatchNorm

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 5)), jnp.float32)
    bn = TrainBatchNorm()
    params = bn.init(jax.random.PRNGKey(0), x)

    def grads(impl):
        monkeypatch.setenv("MPI4DL_TPU_BN_BWD", impl)

        def loss(params, x):
            y = bn.apply(params, x)
            w = jnp.cos(jnp.arange(y.size, dtype=y.dtype)).reshape(y.shape)
            return jnp.sum(y * w)

        (v, gx), gp = (
            jax.value_and_grad(loss, argnums=1)(params, x),
            jax.grad(loss, argnums=0)(params, x),
        )
        return v, gx, gp

    v_f, gx_f, gp_f = grads("fused")
    v_x, gx_x, gp_x = grads("xla")
    np.testing.assert_allclose(float(v_f), float(v_x), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(gx_f), np.asarray(gx_x), rtol=1e-5, atol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        gp_f,
        gp_x,
    )


def test_spatial_window_coverage_check():
    """Spatial windowed ops whose halo can't cover cross-boundary windows
    must fail loudly instead of silently dropping windows."""
    mesh = _mesh(2, 2)
    x = jnp.zeros((1, 8, 8, 2), jnp.float32)
    for mod in (
        Conv2d(features=2, kernel_size=3, padding=0, spatial=True),
        Pool(kind="max", kernel_size=3, strides=2, padding=0, spatial=True),
    ):
        with pytest.raises(ValueError, match="cover tile-boundary windows"):
            fn = shard_map(
                lambda t, m=mod: m.apply({"params": {}}, t),
                mesh=mesh,
                in_specs=(SPEC,),
                out_specs=SPEC,
                check_vma=False,
            )
            jax.eval_shape(fn, jax.ShapeDtypeStruct(x.shape, x.dtype))
