"""Worker process for the real multi-process integration test
(``test_multihost_spawn.py``). Two of these form a 2-host world (2 CPU
devices each, Gloo collectives over localhost) and each validates real
training steps against an in-process single-device golden model.

Golden-comparison note: BatchNorm statistics are computed per data-shard in
the distributed run, so the golden run uses ``parts`` microbatching with
microbatch contents equal to the distributed per-device shards — then both
compute identical BN groups and the losses must match exactly.

Usage: python _multihost_worker.py <process_id> <coordinator_port>
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

pid, port = int(sys.argv[1]), int(sys.argv[2])

from mpi4dl_tpu.parallel import multihost  # noqa: E402  (before device use)

# Exercises the wrapper itself: explicit args configure the world, so any
# init failure must propagate (never silently fall back to single-host).
multihost.initialize_distributed(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from mpi4dl_tpu.config import ParallelConfig  # noqa: E402
from mpi4dl_tpu.models.resnet import get_resnet_v1  # noqa: E402
from mpi4dl_tpu.parallel.partition import init_cells  # noqa: E402
from mpi4dl_tpu.parallel.pipeline import PipelineTrainer  # noqa: E402
from mpi4dl_tpu.train import (  # noqa: E402
    Trainer,
    TrainState,
    make_optimizer,
    single_device_step,
)

# Deterministic global batch, identical on both hosts; each host feeds only
# its local shard.
rng = np.random.default_rng(0)
GB = 8
x = rng.standard_normal((GB, 32, 32, 3)).astype(np.float32)
y = rng.integers(0, 10, size=(GB,)).astype(np.int32)
cells = get_resnet_v1(depth=8)


def golden_loss(parts):
    """Single-device step with per-microbatch BN groups of size GB/parts."""
    _, step = single_device_step(cells, parts=parts)
    params = init_cells(cells, jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    tx = make_optimizer()
    st = TrainState(
        params=params, opt_state=tx.init(params), step=jnp.zeros((), jnp.int32)
    )
    _, metrics = step(st, jnp.asarray(x), jnp.asarray(y))
    return float(metrics["loss"])


# -- case 1: DP over both hosts (data axis crosses processes) ---------------
# 4 data coords, per-device batch 2; coords {2p, 2p+1} live on host p, so
# host p's local shard is the contiguous x[4p:4p+4] and the assembled global
# batch is in canonical order. Golden = parts=4 (BN groups of 2, identical).
cfg = ParallelConfig(
    batch_size=GB, split_size=1, spatial_size=0, data_parallel=4, image_size=32
)
mesh = multihost.make_multihost_mesh(cfg)
trainer = Trainer(cells, num_spatial_cells=0, config=cfg, mesh=mesh)
assert multihost.local_batch_size(mesh, GB) == GB // 2
assert multihost.data_shard(mesh) == (pid, 2), multihost.data_shard(mesh)
state = trainer.init(jax.random.PRNGKey(0), x.shape)
lo = pid * (GB // 2)
xs, ys = trainer.shard_batch(x[lo : lo + GB // 2], y[lo : lo + GB // 2])
assert xs.shape == (GB, 32, 32, 3), xs.shape  # global batch assembled
_, metrics = trainer.train_step(state, xs, ys)
got = float(metrics["loss"])
want = golden_loss(parts=4)
assert abs(got - want) < 1e-4, (got, want)
print(f"proc {pid}: DP case OK loss={got:.6f}", flush=True)

# -- case 2: DP x pipeline (pipe axis inside each host) ---------------------
# Global microbatch m must be x[4m:4m+4]; within it, data coord d holds rows
# [2d:2d+2]. Host p (= data coord p here) therefore feeds, for each of its
# local parts m: x[4m+2p : 4m+2p+2]. BN groups of 2 → golden parts=4.
cfg2 = ParallelConfig(
    batch_size=GB, parts=2, split_size=2, spatial_size=0, data_parallel=2,
    image_size=32,
)
mesh2 = multihost.make_multihost_mesh(cfg2)
t2 = PipelineTrainer(cells, cfg2, mesh=mesh2)
assert multihost.local_batch_size(mesh2, GB) == GB // 2
local_rows = np.concatenate([x[4 * m + 2 * pid : 4 * m + 2 * pid + 2] for m in (0, 1)])
local_labels = np.concatenate(
    [y[4 * m + 2 * pid : 4 * m + 2 * pid + 2] for m in (0, 1)]
)
state2 = t2.init(jax.random.PRNGKey(0))
xs2, ys2 = t2.shard_batch(local_rows, local_labels)
_, m2 = t2.train_step(state2, xs2, ys2)
got2 = float(m2["loss"])
want2 = golden_loss(parts=4)
assert abs(got2 - want2) < 1e-4, (got2, want2)
print(f"proc {pid}: DPxPP case OK loss={got2:.6f}", flush=True)

# -- case 3: DP across hosts x SP inside each host (VERDICT r3 #8) ----------
# Mesh (data=2, tile_w=2): data coordinate p is host p's device pair, so the
# batch axis crosses processes while the halo-exchanging tile axis stays on
# host-local devices — the placement contract local_batch_size enforces.
# Each data shard runs BN over its 4 examples (cross-tile pmean restores
# full-image statistics per shard) → golden = parts=2 microbatching.
n_sp = len(cells) - 1
sp_cells = get_resnet_v1(depth=8, spatial_cells=n_sp)
cfg3 = ParallelConfig(
    batch_size=GB,
    split_size=1,
    spatial_size=1,
    num_spatial_parts=(2,),
    slice_method="vertical",
    data_parallel=2,
    image_size=32,
)
mesh3 = multihost.make_multihost_mesh(cfg3)
t3 = Trainer(
    sp_cells, num_spatial_cells=n_sp, config=cfg3, plain_cells=cells, mesh=mesh3
)
assert multihost.local_batch_size(mesh3, GB) == GB // 2
assert multihost.data_shard(mesh3) == (pid, 2), multihost.data_shard(mesh3)
state3 = t3.init(jax.random.PRNGKey(0), x.shape)
lo = pid * (GB // 2)
xs3, ys3 = t3.shard_batch(x[lo : lo + GB // 2], y[lo : lo + GB // 2])
assert xs3.shape == (GB, 32, 32, 3), xs3.shape
# The per-device shards really are half-width image tiles: SP is live.
tile_shapes = {s.data.shape for s in xs3.addressable_shards}
assert tile_shapes == {(GB // 2, 32, 16, 3)}, tile_shapes
_, m3 = t3.train_step(state3, xs3, ys3)
got3 = float(m3["loss"])
want3 = golden_loss(parts=2)
assert abs(got3 - want3) < 1e-4, (got3, want3)
print(f"proc {pid}: DPxSP case OK loss={got3:.6f}", flush=True)

# -- case 4: the placement contract REJECTS tile axes that cross hosts ------
# Hand-build the adversarial mesh (each tile_w pair takes one device from
# each host): halo rings would ride DCN — local_batch_size must refuse, not
# silently run slow (multihost.py docstring).
from jax.sharding import Mesh  # noqa: E402

devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
bad = np.array(
    [[[[devs[0], devs[2]]]], [[[devs[1], devs[3]]]]]
)  # (data=2, pipe=1, tile_h=1, tile_w=2), tile_w spans processes
bad_mesh = Mesh(bad, ("data", "pipe", "tile_h", "tile_w"))
try:
    multihost.local_batch_size(bad_mesh, GB)
except ValueError as e:
    assert "tile_w" in str(e), e
    print(f"proc {pid}: rejection case OK", flush=True)
else:
    raise AssertionError("cross-host tile axis was not rejected")

print(f"proc {pid}: ALL OK", flush=True)
