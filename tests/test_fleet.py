"""Fault-tolerant replica fleet (ISSUE 8 tentpole): chaos-spec parsing,
router requeue/exactly-once semantics against fake replicas, supervisor
backoff + circuit breaker with trivial no-JAX workers, and the tier-1
chaos drill: router + 2 real replica subprocesses under closed-loop
load, ``kill -9`` one mid-flight, zero accepted-request loss, no double
execution, supervisor replacement, and one requeued request's client →
router → dead-replica → survivor trace join.
"""

import json
import os
import signal
import sys
import textwrap
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mpi4dl_tpu import elastic, telemetry
from mpi4dl_tpu.fleet import (
    ChaosOp,
    FleetRequestError,
    Router,
    parse_chaos_spec,
)
from mpi4dl_tpu.fleet.supervisor import FleetSupervisor
from mpi4dl_tpu.serve.engine import DrainedError, QueueFullError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- chaos spec parsing -------------------------------------------------------


def test_chaos_spec_parsing_goldens():
    assert parse_chaos_spec("kill:1") == ChaosOp("kill", target=1)
    assert parse_chaos_spec("wedge:0@2.5") == ChaosOp(
        "wedge", target=0, at_s=2.5
    )
    assert parse_chaos_spec("blackhole@3s") == ChaosOp("blackhole", at_s=3.0)
    op = parse_chaos_spec("delay-scrape:1=3@2")
    assert (op.action, op.target, op.seconds, op.at_s) == (
        "delay-scrape", 1, 3.0, 2.0
    )
    assert op.describe() == "delay-scrape:r1=3s@+2s"
    # ISSUE 10: the straggler drill — slow a replica's SERVING path.
    op = parse_chaos_spec("delay:1=0.3@2")
    assert (op.action, op.target, op.seconds, op.at_s) == (
        "delay", 1, 0.3, 2.0
    )
    assert op.describe() == "delay:r1=0.3s@+2s"


def test_chaos_spec_errors():
    for bad in ("explode:1", "kill:x", "", "kill:1@@2"):
        with pytest.raises(ValueError):
            parse_chaos_spec(bad)


# -- fake replicas: the router's unit-test doubles ----------------------------


class _FakeReplica:
    """A predict/healthz endpoint with scriptable behavior — the router
    sees a real HTTP surface without paying an engine compile."""

    def __init__(self, mode="ok"):
        self.mode = mode
        self.served_trace_ids: "list[str]" = []
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, status, payload):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, {"healthy": True, "queue_depth": 0})
                else:
                    self._reply(404, {})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length).decode())
                if fake.mode == "queue_full_once":
                    fake.mode = "ok"
                    self._reply(429, {
                        "ok": False, "error": "queue_full",
                        "retry_after_s": 0.01,
                    })
                    return
                if fake.mode == "error":
                    self._reply(500, {"ok": False, "error": "boom"})
                    return
                fake.served_trace_ids.append(req["trace_id"])
                x = np.zeros(4, np.float32)
                import base64

                self._reply(200, {
                    "ok": True,
                    "logits_b64": base64.b64encode(x.tobytes()).decode(),
                    "dtype": "float32", "shape": [4],
                    "trace_id": req["trace_id"],
                    "engine_e2e_s": 0.001, "pid": os.getpid(),
                })

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _mk_router(**kw):
    kw.setdefault("example_shape", (2, 2, 3))
    kw.setdefault("default_deadline_s", 10.0)
    kw.setdefault("inflight_per_replica", 2)
    kw.setdefault("health_interval_s", 0.05)
    return Router(**kw)


def test_router_serves_and_balances_across_fakes():
    fakes = [_FakeReplica(), _FakeReplica()]
    router = _mk_router()
    try:
        for i, f in enumerate(fakes):
            router.add_replica(f"r{i}", f.url, health_url=f.url)
        futs = [
            router.submit(np.zeros((2, 2, 3), np.float32))
            for _ in range(16)
        ]
        for fut in futs:
            out = fut.result(timeout=10)
            assert out.shape == (4,)
            assert fut.trace_id  # propagation surface on the future
            assert fut.e2e_latency_s == pytest.approx(0.001)
        s = router.stats()
        assert s["served"] == 16 and s["failed"] == 0
        # Both replicas took work (2 in-flight slots each; 16 requests).
        assert len(fakes[0].served_trace_ids) > 0
        assert len(fakes[1].served_trace_ids) > 0
        assert router.registry.get("fleet_requests_total").value(
            outcome="served"
        ) == 16
    finally:
        router.stop(drain=False)
        for f in fakes:
            f.close()


def test_router_requeues_dead_replica_onto_survivor():
    """One replica is a dead port (connection refused), one serves: every
    future must still resolve with a result, the dead attempts count as
    dispatch errors + requeues, and the dead replica is marked down."""
    dead = _FakeReplica()
    dead_url = dead.url
    dead.close()  # guaranteed-refused port
    alive = _FakeReplica()
    router = _mk_router(max_attempts=4)
    try:
        router.add_replica("dead", dead_url, health_url=dead_url)
        router.add_replica("alive", alive.url, health_url=alive.url)
        futs = [
            router.submit(np.zeros((2, 2, 3), np.float32))
            for _ in range(12)
        ]
        for fut in futs:
            assert fut.result(timeout=15).shape == (4,)
        s = router.stats()
        assert s["served"] == 12 and s["failed"] == 0
        reps = {r["name"]: r for r in s["replicas"]}
        assert reps["dead"]["healthy"] is False
        err = router.registry.get("fleet_dispatches_total").value(
            replica="dead", outcome="error"
        )
        if err:  # the health scrape may win the race and mark it down
            # before any dispatch — but if a dispatch failed, it MUST
            # have been requeued, never lost.
            assert router.registry.get("fleet_requeues_total").value(
                reason="dispatch_error"
            ) >= 1
    finally:
        router.stop(drain=False)
        alive.close()


def test_router_failed_after_max_attempts_is_typed():
    """Every replica erroring: the future must fail with the TYPED
    FleetRequestError naming attempts/replicas — never hang, never a
    bare socket error."""
    bad = _FakeReplica(mode="error")
    router = _mk_router(max_attempts=2)
    try:
        router.add_replica("bad", bad.url, health_url=bad.url)
        fut = router.submit(np.zeros((2, 2, 3), np.float32))
        with pytest.raises(FleetRequestError) as ei:
            fut.result(timeout=15)
        assert ei.value.attempts == 2
        assert "bad" in ei.value.replicas
        assert router.stats()["failed"] == 1
    finally:
        router.stop(drain=False)
        bad.close()


def test_router_replica_queue_full_requeues_without_burning_attempts():
    """A queue-full bounce is back-pressure, not failure: the request
    retries (on the same fleet) and serves; the bounce lands in
    fleet_requeues_total{reason=replica_queue_full}."""
    fake = _FakeReplica(mode="queue_full_once")
    router = _mk_router(max_attempts=1)
    try:
        router.add_replica("r0", fake.url, health_url=fake.url)
        fut = router.submit(np.zeros((2, 2, 3), np.float32))
        assert fut.result(timeout=15).shape == (4,)
        assert router.registry.get("fleet_requeues_total").value(
            reason="replica_queue_full"
        ) == 1
        assert router.stats()["failed"] == 0
    finally:
        router.stop(drain=False)
        fake.close()


def test_router_admission_and_drain():
    """No replicas: admission still bounds the queue (QueueFullError with
    a retry hint), and stop(drain=False) fails the backlog with the
    typed DrainedError + the drained outcome (not availability burn)."""
    router = _mk_router(max_queue=2)
    futs = [router.submit(np.zeros((2, 2, 3), np.float32))
            for _ in range(2)]
    with pytest.raises(QueueFullError) as ei:
        router.submit(np.zeros((2, 2, 3), np.float32))
    assert ei.value.retry_after_s is not None
    router.stop(drain=False)
    for fut in futs:
        with pytest.raises(DrainedError):
            fut.result(timeout=5)
    assert router.registry.get("fleet_requests_total").value(
        outcome="drained"
    ) == 2
    assert router.registry.get("fleet_requests_total").value(
        outcome="rejected_queue_full"
    ) == 1


def test_router_remove_replica_requeue_is_exactly_once():
    """remove_replica requeues the in-flight ledger; a later stale
    requeue for the same dispatch epoch is a no-op (the guard that
    prevents a dead replica's late-failing RPC thread from re-enqueueing
    a request a survivor already owns)."""
    router = _mk_router()
    try:
        rec_cls = type(router)._Record if hasattr(type(router), "_Record") \
            else None
        from mpi4dl_tpu.fleet.router import _Record

        rec = _Record(
            x=np.zeros((2, 2, 3), np.float32), submit_t=time.monotonic(),
            deadline=time.monotonic() + 30, future=__import__(
                "concurrent.futures", fromlist=["Future"]
            ).Future(), trace_id="t-1",
        )
        rec.state, rec.epoch = "inflight", 1
        assert router._requeue(rec, 1, reason="replica_removed",
                               count_attempt=False) is True
        assert rec.state == "pending"
        # Stale epoch (or already-pending state): no-op, no double count.
        assert router._requeue(rec, 1, reason="replica_removed",
                               count_attempt=False) is False
        assert router.stats()["requeued"] == 1
        del rec_cls
    finally:
        router.stop(drain=False)


# -- supervisor: breaker + restart accounting with no-JAX workers -------------


def _stub_worker(tmp_path, body: str) -> "list[str]":
    """A worker stand-in honoring the --ready-file contract."""
    path = tmp_path / "stub_worker.py"
    path.write_text(textwrap.dedent(body))
    return [sys.executable, str(path)]


def _mk_supervisor(tmp_path, cmd, **kw):
    sup = FleetSupervisor(
        [], registry=telemetry.MetricsRegistry(),
        base_dir=str(tmp_path / "fleet"),
        reconcile_interval_s=0.05,
        heartbeat_timeout_s=None,
        unhealthy_after=10_000,  # stubs serve no /healthz
        backoff_base_s=0.01, backoff_max_s=0.05,
        spawn_timeout_s=30.0,
        **kw,
    )
    sup._worker_cmd = cmd  # the stub replaces `python -m ...worker`
    return sup


def test_supervisor_replaces_dead_replica_and_counts_restart(tmp_path):
    cmd = _stub_worker(tmp_path, """
        import json, os, sys, time
        ready = sys.argv[sys.argv.index("--ready-file") + 1]
        tmp = ready + ".tmp"
        json.dump({"pid": os.getpid(), "predict_port": 1,
                   "metrics_port": 1}, open(tmp, "w"))
        os.replace(tmp, ready)
        time.sleep(3600)
    """)
    events = telemetry.JsonlWriter(str(tmp_path / "events"))
    sup = _mk_supervisor(tmp_path, cmd, replicas=1, events=events)
    try:
        sup.start()
        sup.wait_ready(timeout_s=30)
        slot = sup.slot_by_index(0)
        pid = slot.pid
        os.kill(pid, signal.SIGKILL)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sup.running_count() == 1 and slot.pid != pid:
                break
            time.sleep(0.05)
        assert slot.pid != pid and slot.state == "running"
        assert sup.restarts == 1
        assert sup.registry.get("fleet_replica_restarts_total").value(
            replica="r0", reason="exit"
        ) == 1
        assert sup.last_recovery_s is not None
        assert sup.registry.get("fleet_recovery_seconds").value() \
            == sup.last_recovery_s
        # The restart landed as the schema-valid elastic.restart event.
        events.close()
        evs = telemetry.read_events(events.path)
        restarts = [e for e in evs if e.get("name") == "elastic.restart"]
        assert restarts and restarts[0]["attrs"]["replica"] == "r0"
    finally:
        sup.close()


def test_supervisor_circuit_breaker_trips_and_pages(tmp_path):
    """A crash-looping replica: after K failures in the window the slot
    goes circuit_open — no more respawns — and the page rides the stock
    alert machinery (alert_active gauge + alert.transition event)."""
    cmd = _stub_worker(tmp_path, "raise SystemExit(3)")
    events = telemetry.JsonlWriter(str(tmp_path / "events"))
    sup = _mk_supervisor(
        tmp_path, cmd, replicas=1, events=events,
        breaker_max_restarts=2, breaker_window_s=60.0,
    )
    try:
        sup.start()
        deadline = time.monotonic() + 30
        slot = None
        while time.monotonic() < deadline:
            slot = sup.slot_by_index(0)
            if slot is not None and slot.state == "circuit_open":
                break
            time.sleep(0.05)
        assert slot is not None and slot.state == "circuit_open"
        assert slot.breaker.tripped
        assert sup.restarts == 3  # 2 allowed restarts + the tripping one
        assert sup.registry.get("alert_active").value(
            alert="fleet_circuit_r0", severity="page"
        ) == 1.0
        # No further spawns while open.
        n = sup.restarts
        time.sleep(0.3)
        assert sup.restarts == n
        events.close()
        evs = telemetry.read_events(events.path)
        trans = [e for e in evs if e.get("name") == "alert.transition"]
        assert any(
            t["attrs"]["alert"] == "fleet_circuit_r0"
            and t["attrs"]["to"] == "firing" for t in trans
        )
        # Operator override closes the circuit and respawning resumes.
        sup.reset_breaker("r0")
        assert sup.slot_by_index(0).state in ("backoff", "starting")
        assert sup.registry.get("alert_active").value(
            alert="fleet_circuit_r0", severity="page"
        ) == 0.0
    finally:
        sup.close()


def test_breaker_page_auto_files_log_tail_and_oom_report(tmp_path):
    """ISSUE satellite: the firing circuit-open transition carries an
    auto-filed evidence bundle — the dead worker's log tail and the
    latest oom.report from the fleet telemetry dir — the two pulls the
    runbook previously collected by hand."""
    cmd = _stub_worker(tmp_path, """
        import sys
        print("boom: synthetic compile failure in stub worker",
              file=sys.stderr, flush=True)
        raise SystemExit(3)
    """)
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    oom_ev = {
        "ts": 1.0, "kind": "event", "name": "oom.report",
        "attrs": {"program": "serve_predict", "bucket": 32,
                  "parsed": {"used": 123, "limit": 456}},
    }
    with open(tdir / "telemetry-w.jsonl", "w") as f:
        f.write(json.dumps({"ts": 0.5, "kind": "event",
                            "name": "engine.start", "attrs": {}}) + "\n")
        f.write(json.dumps(oom_ev) + "\n")
    events = telemetry.JsonlWriter(str(tmp_path / "events"))
    env = dict(os.environ, MPI4DL_TPU_TELEMETRY_DIR=str(tdir))
    sup = _mk_supervisor(
        tmp_path, cmd, replicas=1, events=events, env=env,
        breaker_max_restarts=2, breaker_window_s=60.0,
    )
    try:
        sup.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            slot = sup.slot_by_index(0)
            if slot is not None and slot.state == "circuit_open":
                break
            time.sleep(0.05)
        assert sup.slot_by_index(0).state == "circuit_open"
        events.close()
        evs = telemetry.read_events(events.path)
        firing = [
            e for e in evs
            if e.get("name") == "alert.transition"
            and e["attrs"].get("to") == "firing"
        ]
        assert firing, [e.get("name") for e in evs]
        evidence = firing[0]["attrs"]["evidence"]
        assert "boom: synthetic compile failure" in evidence["log_tail"]
        assert evidence["log_path"].endswith("r0.log")
        assert evidence["oom_report"]["attrs"]["program"] == "serve_predict"
        # Non-firing transitions (the reset below) carry no bundle.
        sup.reset_breaker("r0")
    finally:
        sup.close()


def test_breaker_evidence_degrades_without_log_or_telemetry(tmp_path):
    """No telemetry dir configured and no oom history: the page still
    fires, with whatever evidence exists (the log tail)."""
    cmd = _stub_worker(tmp_path, "raise SystemExit(4)")
    events = telemetry.JsonlWriter(str(tmp_path / "events"))
    env = dict(os.environ)
    env.pop("MPI4DL_TPU_TELEMETRY_DIR", None)
    sup = _mk_supervisor(
        tmp_path, cmd, replicas=1, events=events, env=env,
        breaker_max_restarts=1, breaker_window_s=60.0,
    )
    try:
        sup.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            slot = sup.slot_by_index(0)
            if slot is not None and slot.state == "circuit_open":
                break
            time.sleep(0.05)
        assert sup.slot_by_index(0).state == "circuit_open"
        events.close()
        firing = [
            e for e in telemetry.read_events(events.path)
            if e.get("name") == "alert.transition"
            and e["attrs"].get("to") == "firing"
        ]
        assert firing
        evidence = firing[0]["attrs"]["evidence"]
        assert "oom_report" not in evidence
        assert "log_tail" in evidence  # the empty-but-present worker log
    finally:
        sup.close()


# -- elastic satellites -------------------------------------------------------


def test_full_jitter_backoff_deterministic():
    rng = lambda: 1.0  # noqa: E731 — upper envelope
    assert elastic.full_jitter_backoff(1, 0.5, 30.0, rng) == 0.5
    assert elastic.full_jitter_backoff(2, 0.5, 30.0, rng) == 1.0
    assert elastic.full_jitter_backoff(8, 0.5, 30.0, rng) == 30.0  # capped
    assert elastic.full_jitter_backoff(3, 0.5, 30.0, lambda: 0.5) == 1.0
    assert elastic.full_jitter_backoff(0, 0.5, 30.0, rng) == 0.0
    assert elastic.full_jitter_backoff(3, 0.0, 30.0, rng) == 0.0


def test_restart_breaker_windowed():
    t = [0.0]
    br = elastic.RestartBreaker(2, window_s=10.0, clock=lambda: t[0])
    for _ in range(2):
        br.record_failure()
        assert br.allow()
    br.record_failure()
    assert not br.allow() and br.tripped  # 3 failures inside the window
    br.reset()
    # Same 3 failures spread past the window: old ones age out.
    for dt in (0.0, 11.0, 22.0):
        t[0] = dt
        br.record_failure()
        assert br.allow(), dt
    assert br.state()["failures_in_window"] == 1


def test_supervise_backoff_and_restart_event(tmp_path):
    """ISSUE satellite: supervise() restarts with exponential full-jitter
    backoff and emits a schema-valid elastic.restart event per restart."""
    marker = tmp_path / "ok.txt"
    w = tmp_path / "worker.py"
    w.write_text(textwrap.dedent(f"""
        import sys
        if "--resume" not in sys.argv:
            sys.exit(3)
        open({str(marker)!r}, "w").write("ok")
    """))
    events = telemetry.JsonlWriter(str(tmp_path / "ev"))
    sleeps = []
    msgs = []
    rc = elastic.supervise(
        [str(w)], max_restarts=2, poll_interval=0.05,
        backoff_base_s=0.5, rng=lambda: 1.0, _sleep=sleeps.append,
        events=events, _print=msgs.append,
    )
    assert rc == 0 and marker.exists()
    assert sleeps == [0.5]  # attempt 1, full-jitter upper envelope
    assert any("after 0.50s backoff" in m for m in msgs)
    events.close()
    evs = telemetry.read_events(events.path)  # read_events validates
    restarts = [e for e in evs if e["name"] == "elastic.restart"]
    assert len(restarts) == 1
    assert restarts[0]["attrs"]["attempt"] == 1
    assert restarts[0]["attrs"]["backoff_s"] == 0.5
    assert restarts[0]["attrs"]["reason"] == "rc=3"


def test_supervise_windowed_breaker_gives_up(tmp_path):
    w = tmp_path / "crash.py"
    w.write_text("raise SystemExit(7)")
    msgs = []
    rc = elastic.supervise(
        [str(w)], max_restarts=2, restart_window_s=300.0,
        resume_arg=None, poll_interval=0.05, backoff_base_s=0.0,
        _print=msgs.append,
    )
    assert rc == 7
    assert any("within 300s" in m for m in msgs)


# -- the straggler chaos drill (ISSUE 10) -------------------------------------


def test_fleet_chaos_delay_drill_flags_straggler(tmp_path):
    """ISSUE 10 satellite: 2 real replica workers under router load, the
    chaos ``delay`` action slows r1's serving path mid-run — r1 stays
    HEALTHY (keeps serving, /healthz green, nothing restarts it), and
    only the federation-side skew scoring names it:
    ``fleet_replica_skew{replica="r1"}`` over the straggler factor, the
    ``replica_straggler`` advisory page firing on the aggregator's
    /alertz with a transition naming r1, and the router's fleet latency
    histogram carrying exemplar trace ids for the slow bucket."""
    from mpi4dl_tpu.fleet.chaos import inject, parse_chaos_spec
    from mpi4dl_tpu.fleet.replica import ReplicaProcess, worker_cmd
    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.telemetry.federation import FederatedAggregator

    tele = str(tmp_path / "tele")
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    procs = [
        ReplicaProcess(
            f"r{i}",
            worker_cmd(["--image-size", "16", "--max-batch", "2",
                        "--telemetry-dir", tele]),
            base_dir=str(tmp_path / "fleet"),
            env=env,
            log_path=str(tmp_path / f"r{i}.log"),
        )
        for i in range(2)
    ]
    router = Router(
        example_shape=(16, 16, 3), inflight_per_replica=4,
        health_interval_s=0.1, telemetry_dir=tele,
    )
    agg = None
    try:
        for p in procs:
            p.spawn()
        ports = [p.wait_ready(timeout_s=420.0) for p in procs]
        for p, pp in zip(procs, ports):
            router.add_replica(
                p.name,
                f"http://127.0.0.1:{pp['predict_port']}",
                f"http://127.0.0.1:{pp['metrics_port']}",
            )
        agg = FederatedAggregator(
            replicas={
                p.name: f"http://127.0.0.1:{pp['metrics_port']}"
                for p, pp in zip(procs, ports)
            },
            straggler_factor=4.0, straggler_min_count=20,
        )
        x = np.zeros((16, 16, 3), np.float32)

        # Phase 1 — healthy baseline: both replicas serve, nobody skews.
        rep = run_closed_loop(router, 80, concurrency=8, deadline_s=60.0)
        assert rep["served"] == 80 and rep["errors"] == 0
        agg.scrape_once()
        assert agg.straggler_alert.state == "inactive"

        # Phase 2 — inject the delay through the real chaos plumbing
        # (spec grammar → /chaos → delay_predict), via a stub supervisor
        # exposing slot_by_index like the CLI's.
        class _Slots:
            def slot_by_index(self, i):
                import types

                p = procs[i]
                return types.SimpleNamespace(
                    name=p.name, pid=p.pid,
                    client=router._replicas[p.name].client,
                )

        # 1 s/batch: far above the shared CPU box's own tail noise, so
        # the straggler's p99 bucket separates from the healthy
        # replica's under any load jitter.
        record = inject(parse_chaos_spec("delay:1=1"), _Slots())
        assert record["applied"] == "delay_predict"

        rep = run_closed_loop(router, 40, concurrency=8, deadline_s=60.0)
        assert rep["served"] == 40 and rep["errors"] == 0  # slow, not down
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            agg.scrape_once()
            skew = agg.last_skew.get("skew", {})
            if skew.get("r1", 0) >= 4.0:
                break
            # The delayed replica keeps absorbing a trickle (health says
            # yes), so its own histogram keeps inflating.
            run_closed_loop(router, 16, concurrency=4, deadline_s=60.0)
        skew = agg.last_skew["skew"]
        assert skew.get("r1", 0) >= 4.0, agg.last_skew
        assert skew.get("r0", 99) < 4.0, agg.last_skew

        # The gauge + the page, fleet-side.
        assert agg.registry.get("fleet_replica_skew").value(
            replica="r1"
        ) >= 4.0
        assert agg.straggler_alert.state == "firing"
        (t,) = [
            tr for tr in agg.straggler_transitions
            if tr["attrs"]["to"] == "firing"
        ]
        assert t["attrs"]["replica"] == "r1"
        srv = agg.serve(port=0)
        import urllib.request

        alertz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/alertz", timeout=10
        ).read())
        assert any(
            a["name"] == "replica_straggler" and a["state"] == "firing"
            for a in alertz["alerts"]
        )

        # The straggler is HEALTHY the whole time — this failure shape
        # is invisible to every liveness signal the stack had before.
        assert router._replicas["r1"].healthy
        assert procs[1].alive()

        # Router-side: the fleet histogram carries exemplars, and the
        # slow bucket's exemplar is a real trace id (the analyze-tail
        # entry point).
        (series,) = router.registry.get(
            "fleet_request_latency_seconds"
        ).snapshot_series()
        assert series["exemplars"]
        worst = max(
            series["exemplars"].values(), key=lambda e: e["value"]
        )
        assert worst["value"] >= 1.0  # a delayed request tops the map
        # The exemplar is a real loadgen-minted id ("client-<pid>-...");
        # the router only mints its own ("fleet-...") for callers that
        # pass none.
        assert worst["trace_id"].startswith(("client-", "fleet-"))
        assert len(worst["trace_id"].split("-")) == 4
    finally:
        if agg is not None:
            agg.close()
        router.stop(drain=False)
        for p in procs:
            p.terminate(wait_s=10.0)


# -- the tier-1 chaos drill ---------------------------------------------------


def _drill_events(tele_dir) -> "list[dict]":
    events = []
    for f in sorted(os.listdir(tele_dir)):
        if f.endswith(".jsonl"):
            events.extend(
                telemetry.read_events(os.path.join(tele_dir, str(f)))
            )
    return events


def test_fleet_chaos_drill_kill_replica_mid_flight(tmp_path):
    """ISSUE acceptance: 2 replicas under closed-loop load, kill -9 one
    mid-flight. Zero accepted-request loss (every future resolves with a
    result), no request served twice, the survivor absorbs the requeue,
    the supervisor restores the fleet to the (federated)
    autoscale_desired_replicas count, and one requeued request's trace
    joins client → router → dead replica → survivor."""
    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.telemetry.autoscale import AutoscaleConfig

    tele = str(tmp_path / "tele")
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    router = Router(
        example_shape=(16, 16, 3), max_attempts=4,
        inflight_per_replica=4, health_interval_s=0.1,
        telemetry_dir=tele,
    )
    sup = FleetSupervisor(
        ["--image-size", "16", "--max-batch", "2",
         "--telemetry-dir", tele],
        router=router,
        replicas=2, max_replicas=2,
        federation=telemetry.SLOConfig(
            availability=0.999, interval_s=0.5,
            autoscale=AutoscaleConfig(min_replicas=2, max_replicas=2),
        ),
        env=env,
        base_dir=str(tmp_path / "fleet"),
        reconcile_interval_s=0.1,
        heartbeat_timeout_s=5.0,
        backoff_base_s=0.1, backoff_max_s=0.5,
        spawn_timeout_s=420.0,
    )
    n_requests = 400
    try:
        sup.start()
        sup.wait_ready(timeout_s=420)

        report = {}

        def load():
            report.update(run_closed_loop(
                router, n_requests, concurrency=8, deadline_s=120.0,
                events=router.events,
            ))

        t = threading.Thread(target=load)
        t.start()
        # Deterministic mid-flight kill: wait for real traffic, then
        # SIGKILL replica 1 while requests are queued and in flight.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if router.stats()["served"] >= 40:
                break
            time.sleep(0.01)
        victim = sup.slot_by_index(1)
        victim_pid = victim.pid
        os.kill(victim_pid, signal.SIGKILL)
        t.join(timeout=300)
        assert not t.is_alive(), "load run wedged"

        # Zero accepted-request loss: every submitted future resolved,
        # with a RESULT (the survivor absorbed the requeue).
        assert report["served"] == n_requests, report
        assert report["errors"] == 0 and report["deadline_misses"] == 0
        stats = router.stats()
        assert stats["requeued"] >= 1  # the ledger moved to the survivor
        assert router.registry.get("fleet_requeues_total").value(
            reason="replica_removed"
        ) or router.registry.get("fleet_requeues_total").value(
            reason="dispatch_error"
        )

        # Supervisor restores the fleet to the federated desired count.
        assert sup.desired_replicas() == 2
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            if sup.running_count() == 2:
                break
            time.sleep(0.2)
        assert sup.running_count() == 2, sup.state()
        assert sup.restarts >= 1
        assert sup.slot_by_index(1).pid != victim_pid
        assert sup.last_recovery_s is not None
        assert sup.registry.get("fleet_replica_restarts_total").value(
            replica="r1", reason="exit"
        ) >= 1
    finally:
        sup.close()
        router.stop(drain=False)

    # Postmortem over the flushed logs (workers SIGTERMed + router
    # stopped above, so every writer closed/flushed).
    events = _drill_events(tele)
    # No double execution: across every replica's engine log, no trace
    # id was SERVED twice.
    served_by_tid: "dict[str, int]" = {}
    for e in events:
        if (
            e.get("kind") == "span" and e.get("name") == "serve.request"
            and e["attrs"].get("outcome") == "served"
        ):
            served_by_tid[e["trace_id"]] = (
                served_by_tid.get(e["trace_id"], 0) + 1
            )
    doubles = {t: n for t, n in served_by_tid.items() if n > 1}
    assert not doubles, f"double-served trace ids: {doubles}"

    # One requeued request's full lifetime joins under a single id:
    # client segment, the router's dead-replica attempt, the survivor's
    # engine spans.
    groups = telemetry.group_spans_by_trace(events)
    joined = None
    for tid, evs in groups.items():
        disp = [e for e in evs if e["name"] == "router.dispatch"]
        replicas = {e["attrs"]["replica"] for e in disp}
        if len(replicas) > 1 and any(
            e["attrs"]["outcome"] != "ok" for e in disp
        ):
            names = {e["name"] for e in evs}
            if {"client.request", "router.request",
                    "serve.request"} <= names:
                joined = tid
                break
    assert joined is not None, "no requeued trace joined all three hops"
    doc = telemetry.chrome_trace(events, trace_id=joined)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    span_names = {e["name"] for e in xs}
    assert any(n.startswith("rpc_") for n in span_names)  # both hops
    assert {"queue_wait", "device_compute"} <= span_names  # survivor
    assert len({e["pid"] for e in xs}) >= 2  # client+router pid, engine pid
