"""Fault-tolerant replica fleet (ISSUE 8 tentpole): chaos-spec parsing,
router requeue/exactly-once semantics against fake replicas, supervisor
backoff + circuit breaker with trivial no-JAX workers, and the tier-1
chaos drill: router + 2 real replica subprocesses under closed-loop
load, ``kill -9`` one mid-flight, zero accepted-request loss, no double
execution, supervisor replacement, and one requeued request's client →
router → dead-replica → survivor trace join.
"""

import json
import os
import signal
import sys
import textwrap
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mpi4dl_tpu import elastic, telemetry
from mpi4dl_tpu.fleet import (
    ChaosOp,
    FleetRequestError,
    Router,
    parse_chaos_spec,
)
from mpi4dl_tpu.fleet.supervisor import FleetSupervisor
from mpi4dl_tpu.serve.engine import DrainedError, QueueFullError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- chaos spec parsing -------------------------------------------------------


def test_chaos_spec_parsing_goldens():
    assert parse_chaos_spec("kill:1") == ChaosOp("kill", target=1)
    assert parse_chaos_spec("wedge:0@2.5") == ChaosOp(
        "wedge", target=0, at_s=2.5
    )
    assert parse_chaos_spec("blackhole@3s") == ChaosOp("blackhole", at_s=3.0)
    op = parse_chaos_spec("delay-scrape:1=3@2")
    assert (op.action, op.target, op.seconds, op.at_s) == (
        "delay-scrape", 1, 3.0, 2.0
    )
    assert op.describe() == "delay-scrape:r1=3s@+2s"
    # ISSUE 10: the straggler drill — slow a replica's SERVING path.
    op = parse_chaos_spec("delay:1=0.3@2")
    assert (op.action, op.target, op.seconds, op.at_s) == (
        "delay", 1, 0.3, 2.0
    )
    assert op.describe() == "delay:r1=0.3s@+2s"


def test_chaos_spec_errors():
    for bad in ("explode:1", "kill:x", "", "kill:1@@2"):
        with pytest.raises(ValueError):
            parse_chaos_spec(bad)


def test_chaos_spec_router_goldens():
    """ISSUE 12: the router failure domain joins the spec grammar —
    ``kill:router[:N]`` targets a front-door router process."""
    op = parse_chaos_spec("kill:router")
    assert (op.action, op.domain, op.target) == ("kill", "router", 0)
    assert op.describe() == "kill:router0@+1s"
    op = parse_chaos_spec("kill:router:1@2.5")
    assert (op.action, op.domain, op.target, op.at_s) == (
        "kill", "router", 1, 2.5
    )
    assert op.describe() == "kill:router1@+2.5s"
    # Replica specs are untouched (domain defaults to replica).
    assert parse_chaos_spec("kill:1").domain == "replica"
    # Routers have no /chaos surface: soft faults on them are usage
    # errors, not silent no-ops.
    for bad in ("wedge:router", "delay:router:1=3", "blackhole:router"):
        with pytest.raises(ValueError, match="router"):
            parse_chaos_spec(bad)


def test_chaos_spec_flood_goldens():
    """ISSUE 17: the noisy-neighbor drill joins the grammar —
    ``flood:TENANT=RPS[@AT]`` offers a tenant-labeled traffic burst."""
    op = parse_chaos_spec("flood:bulk=500@2")
    assert (op.action, op.domain, op.tenant, op.rps, op.at_s) == (
        "flood", "tenant", "bulk", 500.0, 2.0
    )
    assert op.describe() == "flood:bulk=500rps@+2s"
    # A flood needs both halves: who to flood as, and how hard.
    with pytest.raises(ValueError, match="flood"):
        parse_chaos_spec("flood:bulk")
    with pytest.raises(ValueError, match="flood"):
        parse_chaos_spec("flood=500")
    # Tenant-name targets belong to flood alone.
    with pytest.raises(ValueError, match="replica index"):
        parse_chaos_spec("delay:bulk=3")


def test_chaos_spec_corrupt_goldens():
    """ISSUE 19: the numerics drill joins the grammar —
    ``corrupt:REPLICA[=BITS][@AT]`` flips exponent bits in a live
    replica's param buffer (BITS rides the generic =N spec field)."""
    op = parse_chaos_spec("corrupt:1@2")
    assert (op.action, op.target, op.seconds, op.at_s) == (
        "corrupt", 1, 3.0, 2.0  # 3 bits by default
    )
    assert op.describe() == "corrupt:r1=3b@+2s"
    op = parse_chaos_spec("corrupt:1=8@2")
    assert op.seconds == 8.0
    assert op.describe() == "corrupt:r1=8b@+2s"
    # Routers hold no params: corrupt on a router target is a usage
    # error, same as every other non-kill router action.
    with pytest.raises(ValueError, match="router"):
        parse_chaos_spec("corrupt:router")
    # Zero (or fractional-zero) bits is a spec error, not a no-op drill.
    with pytest.raises(ValueError, match="at least 1 bit"):
        parse_chaos_spec("corrupt:1=0.5")
    with pytest.raises(ValueError):
        parse_chaos_spec("corrupt:1=0")


def test_chaos_inject_self_labels_event_log_and_flight(tmp_path):
    """Satellite golden (ISSUE 20): every injection writes a
    schema-valid ``chaos.injected`` event to the fleet event log AND
    the flight ring BEFORE the fault lands — the incident engine's
    first-cause table blames the drill from the log alone."""
    import types

    from mpi4dl_tpu.fleet.chaos import inject

    writer = telemetry.JsonlWriter(str(tmp_path))
    flight = telemetry.FlightRecorder()
    killed = []
    slot = types.SimpleNamespace(
        name="r1", pid=4242, kill_hard=lambda: killed.append(True),
    )
    sup = types.SimpleNamespace(
        slot_by_index=lambda i: slot, _events=writer, _flight=flight,
    )
    record = inject(parse_chaos_spec("kill:1"), sup)
    writer.close()
    assert killed and record["pid"] == 4242
    evs = [
        e for e in telemetry.read_events(writer.path)
        if e["name"] == "chaos.injected"
    ]
    assert len(evs) == 1
    ev = telemetry.validate_event(evs[0])
    assert ev["attrs"] == {
        "op": "kill:r1@+1s", "action": "kill", "domain": "replica",
        "target": "r1", "at_s": 1.0, "pid": 4242,
    }
    assert ev["ts"] == pytest.approx(record["ts"])
    # The flight ring mirrors the label (a crash dump carries the
    # cause even if the JSONL writer never flushed).
    ring = [e for e in flight.tail() if e["name"] == "chaos.injected"]
    assert len(ring) == 1 and ring[0]["attrs"]["op"] == "kill:r1@+1s"
    # A broken/missing telemetry surface must never fail an injection.
    bare = types.SimpleNamespace(slot_by_index=lambda i: slot)
    assert inject(parse_chaos_spec("kill:1"), bare)["pid"] == 4242


# -- router recovery journal (ISSUE 12 tentpole) ------------------------------


def test_journal_write_scan_goldens(tmp_path):
    """Accept/done lifecycle: a completed request is NOT an orphan (the
    stale-entry no-op), an accepted-only one is, an expired one is
    dropped as expired, and the payload round-trips bit-exact."""
    from mpi4dl_tpu.fleet.journal import RouterJournal, scan

    path = str(tmp_path / "rt0.journal.jsonl")
    j = RouterJournal(path)
    x = np.arange(12, dtype=np.float32).reshape(2, 2, 3)
    j.accept("t-done", x, 30.0, slo_class="tight")
    j.dispatch("t-done", "r0", 1)
    j.done("t-done", "served")
    j.accept("t-orphan", x * 2, 30.0, slo_class=None)
    j.dispatch("t-orphan", "r1", 1)
    j.accept("t-expired", x, 0.0)  # deadline already passed at scan
    j.close()

    s = scan(path)
    assert s.completed == 1
    assert s.expired == 1
    assert [o.trace_id for o in s.orphans] == ["t-orphan"]
    orphan = s.orphans[0]
    np.testing.assert_array_equal(orphan.x, x * 2)  # payload round-trip
    assert orphan.remaining_s() > 25
    assert s.last_epoch == 1


def test_journal_epoch_fencing_across_incarnations(tmp_path):
    """The cross-restart fence: incarnation 2 re-accepts incarnation 1's
    orphan and completes it — incarnation 3's scan sees NO orphan (a
    done in any epoch completes the trace id), and a stale journal
    entry for the completed request is a no-op."""
    from mpi4dl_tpu.fleet.journal import RouterJournal, scan

    path = str(tmp_path / "rt0.journal.jsonl")
    x = np.zeros((2, 2, 3), np.float32)
    j1 = RouterJournal(path)
    assert j1.router_epoch == 1
    j1.accept("t-1", x, 60.0)
    j1.close()  # died with t-1 stranded

    j2 = RouterJournal(path)
    assert j2.router_epoch == 2
    assert [o.trace_id for o in j2.recovered.orphans] == ["t-1"]
    assert j2.recovered.orphans[0].router_epoch == 1
    j2.accept("t-1", x, 55.0)  # the replayed re-accept
    j2.done("t-1", "served")
    j2.close()

    j3 = RouterJournal(path)
    assert j3.router_epoch == 3
    assert j3.recovered.orphans == []
    assert j3.recovered.completed == 1
    j3.close()


def test_journal_scan_tolerates_torn_tail_and_missing_file(tmp_path):
    """A SIGKILL mid-append leaves a torn final line; the scanner skips
    it and keeps everything before it. A missing file is an empty scan,
    not an error."""
    from mpi4dl_tpu.fleet.journal import RouterJournal, scan

    assert scan(str(tmp_path / "nope.jsonl")).orphans == []
    path = str(tmp_path / "rt0.journal.jsonl")
    j = RouterJournal(path)
    j.accept("t-1", np.zeros((2, 2, 3), np.float32), 60.0)
    j.close()
    with open(path, "ab") as f:
        f.write(b'{"kind": "done", "trace_id": "t-1"')  # torn mid-write
    s = scan(path)
    assert s.skipped_lines == 1
    assert [o.trace_id for o in s.orphans] == ["t-1"]  # the torn done
    # never became durable — the request is still an orphan


def test_router_replay_dedupes_redispatches_and_expires(tmp_path):
    """A successor router over a predecessor's journal: an orphan a
    replica already SERVED completes as a dedupe no-op (never
    re-executed), a true orphan re-dispatches and serves, and the
    replay counter splits by outcome."""
    from mpi4dl_tpu.fleet.journal import RouterJournal, scan

    path = str(tmp_path / "rt0.journal.jsonl")
    x = np.zeros((2, 2, 3), np.float32)
    j = RouterJournal(path)
    j.accept("t-already-served", x, 60.0)
    j.accept("t-orphan", x, 60.0)
    j.accept("t-completed", x, 60.0)
    j.done("t-completed", "served")   # stale entry: must be a no-op
    j.close()

    fake = _FakeReplica()
    fake.served_trace_ids.append("t-already-served")
    router = _mk_router(journal_path=path, replay_grace_s=0.6)
    try:
        router.add_replica("r0", fake.url, health_url=fake.url)
        assert router.replay_journal() == 2  # completed one not parked
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if router.stats()["replayed"] == 2:
                break
            time.sleep(0.05)
        m = router.registry.get("fleet_router_journal_replays_total")
        assert m.value(outcome="deduped") == 1
        assert m.value(outcome="redispatched") == 1
        # The deduped orphan was NEVER re-executed on the replica; the
        # true orphan was executed exactly once.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            # Wait for the JOURNAL-visible completion too: the replica
            # records the serve before the router's dispatcher thread
            # journals done, so the replica-side signal alone races the
            # final scan below.
            if (
                "t-orphan" in fake.served_trace_ids
                and not scan(path).orphans
            ):
                break
            time.sleep(0.05)
        assert fake.served_trace_ids.count("t-already-served") == 1
        assert fake.served_trace_ids.count("t-orphan") == 1
    finally:
        router.stop(drain=False)
        fake.close()
    # The journal is clean for the NEXT incarnation: everything done.
    s = scan(path)
    assert s.orphans == [] and s.completed == 3


# -- the HA front door client (ISSUE 12 satellite) ----------------------------


def _mk_router_server(fakes, **kw):
    """One Router + HTTP surface over the given fake replicas."""
    from mpi4dl_tpu.fleet.frontdoor import RouterServer

    router = _mk_router(**kw)
    for i, f in enumerate(fakes):
        router.add_replica(f"r{i}", f.url, health_url=f.url)
    return RouterServer(router, metrics_port=None)


def test_router_set_client_fails_over_on_router_death():
    """Two router processes' worth of /submit surface over one replica
    set; killing one mid-run: every future still resolves with a
    result, the failovers are counted per-request (future.failovers)
    and in the loadgen report (router_failovers), and the survivors
    carry the load."""
    from mpi4dl_tpu.fleet.frontdoor import RouterSetClient
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    fake = _FakeReplica()
    servers = [_mk_router_server([fake]) for _ in range(2)]
    client = RouterSetClient(
        {f"rt{i}": f"http://127.0.0.1:{s.port}"
         for i, s in enumerate(servers)},
        example_shape=(2, 2, 3), default_deadline_s=30.0,
        backoff_base_s=0.01, backoff_max_s=0.05, down_s=0.2,
    )
    try:
        rep = run_closed_loop(client, 24, concurrency=4, deadline_s=30.0)
        assert rep["served"] == 24 and rep["errors"] == 0
        assert rep["router_failovers"] == 0

        servers[1].close()  # kill -9 equivalent: connection refused
        rep = run_closed_loop(client, 24, concurrency=4, deadline_s=30.0)
        assert rep["served"] == 24 and rep["errors"] == 0
        assert rep["router_failovers"] >= 1  # the dead router was hit
        assert client.stats()["router_failovers"] >= 1
    finally:
        client.close()
        servers[0].close()
        fake.close()


def test_router_set_client_all_down_is_typed_and_loadgen_retries():
    """Every router down: submit raises the typed, retriable
    FleetUnreachableError with a retry hint — and the loadgen retry
    loop treats it as retriable (counted as router_failovers, not
    queue pressure), succeeding once a router is back."""
    from mpi4dl_tpu.fleet.frontdoor import RouterSetClient
    from mpi4dl_tpu.fleet.replica import FleetUnreachableError
    from mpi4dl_tpu.serve.loadgen import _submit_with_retry, _Tally

    fake = _FakeReplica()
    server = _mk_router_server([fake])
    url = f"http://127.0.0.1:{server.port}"
    server.close()
    client = RouterSetClient(
        {"rt0": url}, example_shape=(2, 2, 3),
        backoff_base_s=0.01, backoff_max_s=0.05, down_s=10.0,
    )
    try:
        # First submit eats the connection-refused in its worker thread
        # and marks the only router down...
        fut = client.submit(np.zeros((2, 2, 3), np.float32),
                            deadline_s=0.3)
        with pytest.raises(Exception):
            fut.result(timeout=10)
        assert fut.failovers >= 1
        # ...so the next admission fails FAST and TYPED.
        with pytest.raises(FleetUnreachableError) as ei:
            client.submit(np.zeros((2, 2, 3), np.float32))
        assert ei.value.retry_after_s is not None

        # Loadgen treats it as retriable with the hint-honoring backoff:
        tally = _Tally()
        out = _submit_with_retry(
            client, np.zeros((2, 2, 3), np.float32), 0.3, "t-x",
            tally, queue_full_retries=2, retry_backoff_s=0.01,
        )
        assert out is None  # budget spent while all routers stay down
        assert tally.router_failovers >= 1
        assert tally.queue_full_retries == 0  # NOT counted as pressure
    finally:
        client.close()
        fake.close()


def test_worker_served_cache_semantics():
    """The replica-side idempotency registry: done answers dedupe,
    in-flight duplicates join the live future, only successes are
    cached, and the capacity bound evicts FIFO."""
    from concurrent.futures import Future

    from mpi4dl_tpu.fleet.worker import _ServedCache

    c = _ServedCache(capacity=2)
    fut = Future()
    c.begin("t-1", fut)
    payload, joined = c.lookup("t-1")
    assert payload is None and joined is fut  # join, don't re-execute
    assert c.served(["t-1", "t-2"]) == ["t-1"]  # in-flight counts
    c.finish("t-1", {"ok": True, "n": 1})
    payload, joined = c.lookup("t-1")
    assert payload == {"ok": True, "n": 1} and joined is None
    # Error outcomes are terminal for the RPC but NOT cached (a retry
    # with fresh budget may succeed).
    c.begin("t-2", Future())
    c.finish("t-2", None)
    assert c.lookup("t-2") == (None, None)
    # FIFO eviction at capacity.
    c.finish("t-3", {"n": 3})
    c.finish("t-4", {"n": 4})
    assert c.lookup("t-1") == (None, None)  # evicted
    assert c.lookup("t-4")[0] == {"n": 4}


# -- fake replicas: the router's unit-test doubles ----------------------------


class _FakeReplica:
    """A predict/healthz endpoint with scriptable behavior — the router
    sees a real HTTP surface without paying an engine compile."""

    def __init__(self, mode="ok", idempotent=False):
        self.mode = mode
        self.idempotent = idempotent  # real replicas' _ServedCache shape:
        # a repeated trace id returns the cached result, no re-execution
        self.served_trace_ids: "list[str]" = []
        self.executions: "dict[str, int]" = {}
        self.cache_hits = 0
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, status, payload):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, {"healthy": True, "queue_depth": 0})
                else:
                    self._reply(404, {})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length).decode())
                if self.path == "/served":
                    self._reply(200, {"served": [
                        t for t in req.get("trace_ids", ())
                        if t in fake.served_trace_ids
                    ]})
                    return
                if fake.mode == "queue_full_once":
                    fake.mode = "ok"
                    self._reply(429, {
                        "ok": False, "error": "queue_full",
                        "retry_after_s": 0.01,
                    })
                    return
                if fake.mode == "error":
                    self._reply(500, {"ok": False, "error": "boom"})
                    return
                tid = req["trace_id"]
                if fake.idempotent and tid in fake.served_trace_ids:
                    fake.cache_hits += 1
                else:
                    fake.executions[tid] = fake.executions.get(tid, 0) + 1
                    fake.served_trace_ids.append(tid)
                x = np.zeros(4, np.float32)
                import base64

                self._reply(200, {
                    "ok": True,
                    "logits_b64": base64.b64encode(x.tobytes()).decode(),
                    "dtype": "float32", "shape": [4],
                    "trace_id": req["trace_id"],
                    "engine_e2e_s": 0.001, "pid": os.getpid(),
                })

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _mk_router(**kw):
    kw.setdefault("example_shape", (2, 2, 3))
    kw.setdefault("default_deadline_s", 10.0)
    kw.setdefault("inflight_per_replica", 2)
    kw.setdefault("health_interval_s", 0.05)
    return Router(**kw)


def test_router_serves_and_balances_across_fakes():
    fakes = [_FakeReplica(), _FakeReplica()]
    router = _mk_router()
    try:
        for i, f in enumerate(fakes):
            router.add_replica(f"r{i}", f.url, health_url=f.url)
        futs = [
            router.submit(np.zeros((2, 2, 3), np.float32))
            for _ in range(16)
        ]
        for fut in futs:
            out = fut.result(timeout=10)
            assert out.shape == (4,)
            assert fut.trace_id  # propagation surface on the future
            assert fut.e2e_latency_s == pytest.approx(0.001)
        s = router.stats()
        assert s["served"] == 16 and s["failed"] == 0
        # Both replicas took work (2 in-flight slots each; 16 requests).
        assert len(fakes[0].served_trace_ids) > 0
        assert len(fakes[1].served_trace_ids) > 0
        assert router.registry.get("fleet_requests_total").value(
            outcome="served"
        ) == 16
    finally:
        router.stop(drain=False)
        for f in fakes:
            f.close()


def test_router_requeues_dead_replica_onto_survivor():
    """One replica is a dead port (connection refused), one serves: every
    future must still resolve with a result, the dead attempts count as
    dispatch errors + requeues, and the dead replica is marked down."""
    dead = _FakeReplica()
    dead_url = dead.url
    dead.close()  # guaranteed-refused port
    alive = _FakeReplica()
    router = _mk_router(max_attempts=4)
    try:
        router.add_replica("dead", dead_url, health_url=dead_url)
        router.add_replica("alive", alive.url, health_url=alive.url)
        futs = [
            router.submit(np.zeros((2, 2, 3), np.float32))
            for _ in range(12)
        ]
        for fut in futs:
            assert fut.result(timeout=15).shape == (4,)
        s = router.stats()
        assert s["served"] == 12 and s["failed"] == 0
        reps = {r["name"]: r for r in s["replicas"]}
        assert reps["dead"]["healthy"] is False
        err = router.registry.get("fleet_dispatches_total").value(
            replica="dead", outcome="error"
        )
        if err:  # the health scrape may win the race and mark it down
            # before any dispatch — but if a dispatch failed, it MUST
            # have been requeued, never lost.
            assert router.registry.get("fleet_requeues_total").value(
                reason="dispatch_error"
            ) >= 1
    finally:
        router.stop(drain=False)
        alive.close()


def test_router_failed_after_max_attempts_is_typed():
    """Every replica erroring: the future must fail with the TYPED
    FleetRequestError naming attempts/replicas — never hang, never a
    bare socket error."""
    bad = _FakeReplica(mode="error")
    router = _mk_router(max_attempts=2)
    try:
        router.add_replica("bad", bad.url, health_url=bad.url)
        fut = router.submit(np.zeros((2, 2, 3), np.float32))
        with pytest.raises(FleetRequestError) as ei:
            fut.result(timeout=15)
        assert ei.value.attempts == 2
        assert "bad" in ei.value.replicas
        assert router.stats()["failed"] == 1
    finally:
        router.stop(drain=False)
        bad.close()


def test_router_retried_probe_completes_from_cache():
    """ISSUE 17 exactly-once: a ``retried:true`` submit probes /served
    across the fleet BEFORE any dispatch; a voucher means the request
    completes from the replica's idempotency cache — the model never
    runs twice for one trace id."""
    fakes = [_FakeReplica(idempotent=True), _FakeReplica(idempotent=True)]
    router = _mk_router()
    try:
        for i, f in enumerate(fakes):
            router.add_replica(f"r{i}", f.url, health_url=f.url)
        x = np.zeros((2, 2, 3), np.float32)
        out = router.submit(x, trace_id="t-x").result(timeout=10)
        assert out.shape == (4,)
        # The client retries after losing the response: same trace id,
        # retried=True. The probe must find the voucher and short-circuit.
        out2 = router.submit(x, trace_id="t-x", retried=True).result(
            timeout=10
        )
        assert out2.shape == (4,)
        assert sum(f.executions.get("t-x", 0) for f in fakes) == 1
        assert router.registry.get("fleet_requests_total").value(
            outcome="served_cached"
        ) == 1
    finally:
        router.stop(drain=False)
        for f in fakes:
            f.close()


def test_router_death_races_parked_retry_exactly_once(tmp_path):
    """THE ISSUE 17 drill: a router dies holding an accepted-but-
    undispatched request in its journal; its successor replays the
    orphan and parks it, while the client's retry races in through a
    SURVIVOR router. Exactly one execution by trace id, and the
    successor's park must resolve as deduped — never a second serve."""
    from mpi4dl_tpu.fleet.journal import RouterJournal, scan

    path = tmp_path / "router.journal"
    x = np.zeros((2, 2, 3), np.float32)
    # The predecessor accepted t-race, journaled it, and died before
    # dispatch: the journal is all that remains.
    j = RouterJournal(str(path))
    j.accept("t-race", x, 60.0)
    j.close()

    fake = _FakeReplica(idempotent=True)
    successor = _mk_router(journal_path=str(path), replay_grace_s=2.0)
    survivor = _mk_router()
    try:
        successor.add_replica("r0", fake.url, health_url=fake.url)
        survivor.add_replica("r0", fake.url, health_url=fake.url)
        # Successor replays: the orphan parks, polling /served for the
        # grace window before it would re-dispatch.
        assert successor.replay_journal() == 1
        # The client retry lands on the survivor while the park is live.
        out = survivor.submit(x, trace_id="t-race", retried=True).result(
            timeout=10
        )
        assert out.shape == (4,)
        # The successor's poll must observe the voucher and dedupe.
        m = successor.registry.get("fleet_router_journal_replays_total")
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if m.value(outcome="deduped") >= 1:
                break
            time.sleep(0.02)
        assert m.value(outcome="deduped") == 1
        # Zero double-executes by trace id — the drill's whole point.
        assert fake.executions.get("t-race", 0) == 1
        successor.stop(drain=True)
        rec = scan(str(path))
        assert not rec.orphans  # the journal closed the loop
    finally:
        for r in (successor, survivor):
            try:
                r.stop(drain=False)
            except Exception:
                pass
        fake.close()


def test_router_quota_shed_at_front_door():
    """ISSUE 17 front-door quotas: over-quota submits shed with the
    typed QuotaExceededError (+ refill-derived retry hint) BEFORE
    taking a queue slot, and the shed is visible in router stats and
    the tenant metrics."""
    from mpi4dl_tpu.tenancy import QuotaExceededError

    fake = _FakeReplica()
    router = _mk_router(tenants="capped=1:1")
    try:
        router.add_replica("r0", fake.url, health_url=fake.url)
        x = np.zeros((2, 2, 3), np.float32)
        out = router.submit(x, tenant="capped").result(timeout=10)
        assert out.shape == (4,)
        with pytest.raises(QuotaExceededError) as ei:
            router.submit(x, tenant="capped")
        assert ei.value.tenant == "capped"
        assert ei.value.retry_after_s == pytest.approx(1.0, rel=0.3)
        s = router.stats()
        assert s["rejected_quota"] == 1
        assert s["tenancy"]["capped"]["rate_rps"] == 1.0
        assert router.registry.get("fleet_requests_total").value(
            outcome="rejected_quota"
        ) == 1
        assert router.registry.get("tenant_quota_sheds_total").value(
            tenant="capped"
        ) == 1
        with pytest.raises(ValueError, match="unknown tenant"):
            router.submit(x, tenant="nobody")
    finally:
        router.stop(drain=False)
        fake.close()


def test_router_replica_queue_full_requeues_without_burning_attempts():
    """A queue-full bounce is back-pressure, not failure: the request
    retries (on the same fleet) and serves; the bounce lands in
    fleet_requeues_total{reason=replica_queue_full}."""
    fake = _FakeReplica(mode="queue_full_once")
    router = _mk_router(max_attempts=1)
    try:
        router.add_replica("r0", fake.url, health_url=fake.url)
        fut = router.submit(np.zeros((2, 2, 3), np.float32))
        assert fut.result(timeout=15).shape == (4,)
        assert router.registry.get("fleet_requeues_total").value(
            reason="replica_queue_full"
        ) == 1
        assert router.stats()["failed"] == 0
    finally:
        router.stop(drain=False)
        fake.close()


def test_router_admission_and_drain():
    """No replicas: admission still bounds the queue (QueueFullError with
    a retry hint), and stop(drain=False) fails the backlog with the
    typed DrainedError + the drained outcome (not availability burn)."""
    router = _mk_router(max_queue=2)
    futs = [router.submit(np.zeros((2, 2, 3), np.float32))
            for _ in range(2)]
    with pytest.raises(QueueFullError) as ei:
        router.submit(np.zeros((2, 2, 3), np.float32))
    assert ei.value.retry_after_s is not None
    router.stop(drain=False)
    for fut in futs:
        with pytest.raises(DrainedError):
            fut.result(timeout=5)
    assert router.registry.get("fleet_requests_total").value(
        outcome="drained"
    ) == 2
    assert router.registry.get("fleet_requests_total").value(
        outcome="rejected_queue_full"
    ) == 1


def test_router_remove_replica_requeue_is_exactly_once():
    """remove_replica requeues the in-flight ledger; a later stale
    requeue for the same dispatch epoch is a no-op (the guard that
    prevents a dead replica's late-failing RPC thread from re-enqueueing
    a request a survivor already owns)."""
    router = _mk_router()
    try:
        rec_cls = type(router)._Record if hasattr(type(router), "_Record") \
            else None
        from mpi4dl_tpu.fleet.router import _Record

        rec = _Record(
            x=np.zeros((2, 2, 3), np.float32), submit_t=time.monotonic(),
            deadline=time.monotonic() + 30, future=__import__(
                "concurrent.futures", fromlist=["Future"]
            ).Future(), trace_id="t-1",
        )
        rec.state, rec.epoch = "inflight", 1
        assert router._requeue(rec, 1, reason="replica_removed",
                               count_attempt=False) is True
        assert rec.state == "pending"
        # Stale epoch (or already-pending state): no-op, no double count.
        assert router._requeue(rec, 1, reason="replica_removed",
                               count_attempt=False) is False
        assert router.stats()["requeued"] == 1
        del rec_cls
    finally:
        router.stop(drain=False)


# -- supervisor: breaker + restart accounting with no-JAX workers -------------


def _stub_worker(tmp_path, body: str) -> "list[str]":
    """A worker stand-in honoring the --ready-file contract."""
    path = tmp_path / "stub_worker.py"
    path.write_text(textwrap.dedent(body))
    return [sys.executable, str(path)]


def _mk_supervisor(tmp_path, cmd, **kw):
    sup = FleetSupervisor(
        [], registry=telemetry.MetricsRegistry(),
        base_dir=str(tmp_path / "fleet"),
        reconcile_interval_s=0.05,
        heartbeat_timeout_s=None,
        unhealthy_after=10_000,  # stubs serve no /healthz
        backoff_base_s=0.01, backoff_max_s=0.05,
        spawn_timeout_s=30.0,
        **kw,
    )
    sup._worker_cmd = cmd  # the stub replaces `python -m ...worker`
    return sup


def test_supervisor_replaces_dead_replica_and_counts_restart(tmp_path):
    cmd = _stub_worker(tmp_path, """
        import json, os, sys, time
        ready = sys.argv[sys.argv.index("--ready-file") + 1]
        tmp = ready + ".tmp"
        json.dump({"pid": os.getpid(), "predict_port": 1,
                   "metrics_port": 1}, open(tmp, "w"))
        os.replace(tmp, ready)
        time.sleep(3600)
    """)
    events = telemetry.JsonlWriter(str(tmp_path / "events"))
    sup = _mk_supervisor(tmp_path, cmd, replicas=1, events=events)
    try:
        sup.start()
        sup.wait_ready(timeout_s=30)
        slot = sup.slot_by_index(0)
        pid = slot.pid
        os.kill(pid, signal.SIGKILL)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sup.running_count() == 1 and slot.pid != pid:
                break
            time.sleep(0.05)
        assert slot.pid != pid and slot.state == "running"
        assert sup.restarts == 1
        assert sup.registry.get("fleet_replica_restarts_total").value(
            replica="r0", reason="exit"
        ) == 1
        assert sup.last_recovery_s is not None
        assert sup.registry.get("fleet_recovery_seconds").value() \
            == sup.last_recovery_s
        # ISSUE 18: cold-respawn phase attribution. This stub reports no
        # phases in its ready handshake, so the whole recovery lands in
        # the supervisor-side "spawn" residual — and the decomposition
        # still sums exactly to fleet_recovery_seconds.
        phases = sup.last_recovery_phases
        assert phases is not None
        assert phases["spawn"] == pytest.approx(sup.last_recovery_s)
        assert sum(phases.values()) == pytest.approx(sup.last_recovery_s)
        assert sup.registry.get("fleet_recovery_phase_seconds").value(
            phase="spawn"
        ) == pytest.approx(sup.last_recovery_s)
        # The restart landed as the schema-valid elastic.restart event.
        events.close()
        evs = telemetry.read_events(events.path)
        restarts = [e for e in evs if e.get("name") == "elastic.restart"]
        assert restarts and restarts[0]["attrs"]["replica"] == "r0"
    finally:
        sup.close()


def test_supervisor_circuit_breaker_trips_and_pages(tmp_path):
    """A crash-looping replica: after K failures in the window the slot
    goes circuit_open — no more respawns — and the page rides the stock
    alert machinery (alert_active gauge + alert.transition event)."""
    cmd = _stub_worker(tmp_path, "raise SystemExit(3)")
    events = telemetry.JsonlWriter(str(tmp_path / "events"))
    sup = _mk_supervisor(
        tmp_path, cmd, replicas=1, events=events,
        breaker_max_restarts=2, breaker_window_s=60.0,
    )
    try:
        sup.start()
        deadline = time.monotonic() + 30
        slot = None
        while time.monotonic() < deadline:
            slot = sup.slot_by_index(0)
            if slot is not None and slot.state == "circuit_open":
                break
            time.sleep(0.05)
        assert slot is not None and slot.state == "circuit_open"
        assert slot.breaker.tripped
        assert sup.restarts == 3  # 2 allowed restarts + the tripping one
        assert sup.registry.get("alert_active").value(
            alert="fleet_circuit_r0", severity="page"
        ) == 1.0
        # No further spawns while open.
        n = sup.restarts
        time.sleep(0.3)
        assert sup.restarts == n
        events.close()
        evs = telemetry.read_events(events.path)
        trans = [e for e in evs if e.get("name") == "alert.transition"]
        assert any(
            t["attrs"]["alert"] == "fleet_circuit_r0"
            and t["attrs"]["to"] == "firing" for t in trans
        )
        # Operator override closes the circuit and respawning resumes.
        sup.reset_breaker("r0")
        assert sup.slot_by_index(0).state in ("backoff", "starting")
        assert sup.registry.get("alert_active").value(
            alert="fleet_circuit_r0", severity="page"
        ) == 0.0
    finally:
        sup.close()


def test_breaker_page_auto_files_log_tail_and_oom_report(tmp_path):
    """ISSUE satellite: the firing circuit-open transition carries an
    auto-filed evidence bundle — the dead worker's log tail and the
    latest oom.report from the fleet telemetry dir — the two pulls the
    runbook previously collected by hand."""
    cmd = _stub_worker(tmp_path, """
        import sys
        print("boom: synthetic compile failure in stub worker",
              file=sys.stderr, flush=True)
        raise SystemExit(3)
    """)
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    oom_ev = {
        "ts": 1.0, "kind": "event", "name": "oom.report",
        "attrs": {"program": "serve_predict", "bucket": 32,
                  "parsed": {"used": 123, "limit": 456}},
    }
    with open(tdir / "telemetry-w.jsonl", "w") as f:
        f.write(json.dumps({"ts": 0.5, "kind": "event",
                            "name": "engine.start", "attrs": {}}) + "\n")
        f.write(json.dumps(oom_ev) + "\n")
    events = telemetry.JsonlWriter(str(tmp_path / "events"))
    env = dict(os.environ, MPI4DL_TPU_TELEMETRY_DIR=str(tdir))
    sup = _mk_supervisor(
        tmp_path, cmd, replicas=1, events=events, env=env,
        breaker_max_restarts=2, breaker_window_s=60.0,
    )
    try:
        sup.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            slot = sup.slot_by_index(0)
            if slot is not None and slot.state == "circuit_open":
                break
            time.sleep(0.05)
        assert sup.slot_by_index(0).state == "circuit_open"
        events.close()
        evs = telemetry.read_events(events.path)
        firing = [
            e for e in evs
            if e.get("name") == "alert.transition"
            and e["attrs"].get("to") == "firing"
        ]
        assert firing, [e.get("name") for e in evs]
        evidence = firing[0]["attrs"]["evidence"]
        assert "boom: synthetic compile failure" in evidence["log_tail"]
        assert evidence["log_path"].endswith("r0.log")
        assert evidence["oom_report"]["attrs"]["program"] == "serve_predict"
        # Non-firing transitions (the reset below) carry no bundle.
        sup.reset_breaker("r0")
    finally:
        sup.close()


def test_breaker_evidence_degrades_without_log_or_telemetry(tmp_path):
    """No telemetry dir configured and no oom history: the page still
    fires, with whatever evidence exists (the log tail)."""
    cmd = _stub_worker(tmp_path, "raise SystemExit(4)")
    events = telemetry.JsonlWriter(str(tmp_path / "events"))
    env = dict(os.environ)
    env.pop("MPI4DL_TPU_TELEMETRY_DIR", None)
    sup = _mk_supervisor(
        tmp_path, cmd, replicas=1, events=events, env=env,
        breaker_max_restarts=1, breaker_window_s=60.0,
    )
    try:
        sup.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            slot = sup.slot_by_index(0)
            if slot is not None and slot.state == "circuit_open":
                break
            time.sleep(0.05)
        assert sup.slot_by_index(0).state == "circuit_open"
        events.close()
        firing = [
            e for e in telemetry.read_events(events.path)
            if e.get("name") == "alert.transition"
            and e["attrs"].get("to") == "firing"
        ]
        assert firing
        evidence = firing[0]["attrs"]["evidence"]
        assert "oom_report" not in evidence
        assert "log_tail" in evidence  # the empty-but-present worker log
    finally:
        sup.close()


# -- warm-pool standby + promotion (ISSUE 12 tentpole) ------------------------

#: A no-JAX worker stand-in that honors the ready handshake AND answers
#: /healthz 200 — the handshake surface standby promotion verifies.
_HEALTHY_STUB = """
    import json, os, sys, threading, time
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class H(BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps({"healthy": True, "queue_depth": 0}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    ready = sys.argv[sys.argv.index("--ready-file") + 1]
    port = httpd.server_address[1]
    tmp = ready + ".tmp"
    json.dump({"pid": os.getpid(), "predict_port": port,
               "metrics_port": port}, open(tmp, "w"))
    os.replace(tmp, ready)
    time.sleep(3600)
"""


def test_warm_pool_promotion_replaces_dead_replica_fast(tmp_path):
    """A serving replica dies with a warm standby up: recovery is a
    PROMOTION — handshake + routing flip, no spawn in the recovery
    path — so fleet_recovery_seconds is sub-spawn; the victim slot
    backfills the pool asynchronously."""
    cmd = _stub_worker(tmp_path, _HEALTHY_STUB)
    router = _mk_router()
    sup = _mk_supervisor(tmp_path, cmd, replicas=1, router=router,
                         warm_pool=1)
    try:
        sup.start()
        sup.wait_ready(timeout_s=30)
        assert sup.standby_count() == 1
        assert sup.registry.get("fleet_standby_replicas").value() == 1
        # Only the serving replica is routed; the standby is warm but
        # invisible to dispatch.
        assert set(router._replicas) == {"r0"}
        victim_pid = sup.slot_by_index(0).pid

        os.kill(victim_pid, signal.SIGKILL)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sup.promotions == 1 and sup.running_count() == 1:
                break
            time.sleep(0.05)
        assert sup.promotions == 1
        assert sup.registry.get("fleet_promotions_total").value() == 1
        # The routing flip happened: r1 (the ex-standby) serves, r0 is
        # out — and recovery was promotion-fast, not spawn-bound.
        assert "r1" in router._replicas and "r0" not in router._replicas
        serving = [s for s in sup.state()["slots"]
                   if s["kind"] == "replica" and s["role"] == "serving"]
        assert [s["name"] for s in serving] == ["r1"]
        assert sup.last_recovery_s is not None
        assert sup.last_recovery_s < 5.0  # flip + handshake, not a spawn
        # ISSUE 18: the phase decomposition attributes a promotion
        # honestly — the whole recovery is routable-again time ("ready"),
        # compile/warm ZERO (the phases the warm pool's idle RAM bought),
        # and the published phases sum exactly to fleet_recovery_seconds.
        phases = sup.last_recovery_phases
        assert phases is not None
        assert phases["compile"] == 0.0 and phases["warm"] == 0.0
        assert phases["spawn"] == 0.0
        assert phases["ready"] == pytest.approx(sup.last_recovery_s)
        assert sum(phases.values()) == pytest.approx(sup.last_recovery_s)
        g = sup.registry.get("fleet_recovery_phase_seconds")
        assert g.value(phase="ready") == pytest.approx(sup.last_recovery_s)
        assert g.value(phase="compile") == 0.0
        # The pool backfills: the victim slot respawns INTO standby.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if sup.standby_count() == 1:
                break
            time.sleep(0.05)
        assert sup.standby_count() == 1
        slot0 = sup.slot_by_index(0)
        assert slot0.role == "standby" and slot0.state == "standby"
        assert set(router._replicas) == {"r1"}  # still exactly one route
    finally:
        sup.close()
        router.stop(drain=False)


def test_promotion_race_dead_standby_falls_back_to_cold_spawn(tmp_path):
    """ISSUE satellite: death DURING promotion — the standby is killed
    right before the serving replica, so the promotion handshake meets
    a corpse. The supervisor must fall back to the cold-spawn path and
    NEVER route the dead standby (no double-route, no phantom
    replica)."""
    cmd = _stub_worker(tmp_path, _HEALTHY_STUB)
    router = _mk_router()
    sup = _mk_supervisor(tmp_path, cmd, replicas=1, router=router,
                         warm_pool=1)
    try:
        sup.start()
        sup.wait_ready(timeout_s=30)
        standby_pid = sup.slot_by_index(1).pid
        serving_pid = sup.slot_by_index(0).pid
        # Kill the standby FIRST (no tick between: the serving death's
        # promotion attempt races the standby's own death handling).
        os.kill(standby_pid, signal.SIGKILL)
        os.kill(serving_pid, signal.SIGKILL)
        dead = {standby_pid, serving_pid}
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            # Recovery means BOTH deaths were processed and replaced
            # with fresh pids — the state gauges alone read green for an
            # instant after the kills, before detection.
            if (
                sup.restarts >= 2
                and sup.running_count() == 1
                and sup.standby_count() == 1
                and not ({sup.slot_by_index(0).pid,
                          sup.slot_by_index(1).pid} & dead)
            ):
                break
            time.sleep(0.05)
        assert sup.running_count() == 1, sup.state()
        assert sup.standby_count() == 1, sup.state()
        assert not ({sup.slot_by_index(0).pid,
                     sup.slot_by_index(1).pid} & dead)
        # No promotion happened: the handshake refused the corpse and
        # recovery went through a cold spawn instead.
        assert sup.promotions == 0
        # Exactly ONE route, and it points at a live process.
        assert len(router._replicas) == 1
        serving = [s for s in sup.state()["slots"]
                   if s["kind"] == "replica" and s["role"] == "serving"]
        assert len(serving) == 1
        assert set(router._replicas) == {serving[0]["name"]}
        assert sup._slots[serving[0]["name"]].proc.alive()
    finally:
        sup.close()
        router.stop(drain=False)


def test_heartbeat_staleness_immune_to_wall_clock_step(tmp_path):
    """ISSUE satellite (monotonic audit): staleness is measured from the
    last observed mtime CHANGE on OUR monotonic clock — a wall-clock
    step (NTP jump, VM resume) that rewrites mtimes into the past must
    NOT mass-expire heartbeats and kill a healthy fleet."""
    from mpi4dl_tpu.fleet.replica import ReplicaProcess

    hb = str(tmp_path / "r0.heartbeat")
    p = ReplicaProcess("r0", ["true"], str(tmp_path), heartbeat_path=hb)
    p._hb_seen = time.monotonic() - 100.0  # long-stale baseline
    p._hb_mtime = None
    elastic.touch(hb)
    assert p.heartbeat_stale_s() < 1.0  # a beat arrived: fresh

    # The wall clock steps BACK one hour mid-run: the file's mtime now
    # reads an hour old. Change-detection treats it as a beat (the
    # mtime CHANGED); comparing mtime to time.time() would declare 1h
    # of staleness and SIGKILL a healthy replica.
    past = time.time() - 3600.0
    os.utime(hb, (past, past))
    assert p.heartbeat_stale_s() < 1.0
    # And with NO further beats, staleness grows on the monotonic clock.
    p._hb_seen = time.monotonic() - 7.5
    p._hb_mtime = os.path.getmtime(hb)
    assert 7.0 < p.heartbeat_stale_s() < 9.0


def test_spawn_age_uses_process_monotonic_clock(tmp_path):
    """The spawn-timeout input comes from the process handle's own
    monotonic clock (spawned_age_s), never `injected_clock() - stamp`
    arithmetic across two different clocks."""
    from mpi4dl_tpu.fleet.replica import ReplicaProcess

    p = ReplicaProcess("r0", ["true"], str(tmp_path))
    assert p.spawned_age_s() == 0.0  # never spawned
    p.spawned_at = time.monotonic() - 3.0
    assert 2.5 < p.spawned_age_s() < 4.0


# -- elastic satellites -------------------------------------------------------


def test_full_jitter_backoff_deterministic():
    rng = lambda: 1.0  # noqa: E731 — upper envelope
    assert elastic.full_jitter_backoff(1, 0.5, 30.0, rng) == 0.5
    assert elastic.full_jitter_backoff(2, 0.5, 30.0, rng) == 1.0
    assert elastic.full_jitter_backoff(8, 0.5, 30.0, rng) == 30.0  # capped
    assert elastic.full_jitter_backoff(3, 0.5, 30.0, lambda: 0.5) == 1.0
    assert elastic.full_jitter_backoff(0, 0.5, 30.0, rng) == 0.0
    assert elastic.full_jitter_backoff(3, 0.0, 30.0, rng) == 0.0


def test_restart_breaker_windowed():
    t = [0.0]
    br = elastic.RestartBreaker(2, window_s=10.0, clock=lambda: t[0])
    for _ in range(2):
        br.record_failure()
        assert br.allow()
    br.record_failure()
    assert not br.allow() and br.tripped  # 3 failures inside the window
    br.reset()
    # Same 3 failures spread past the window: old ones age out.
    for dt in (0.0, 11.0, 22.0):
        t[0] = dt
        br.record_failure()
        assert br.allow(), dt
    assert br.state()["failures_in_window"] == 1


def test_supervise_backoff_and_restart_event(tmp_path):
    """ISSUE satellite: supervise() restarts with exponential full-jitter
    backoff and emits a schema-valid elastic.restart event per restart."""
    marker = tmp_path / "ok.txt"
    w = tmp_path / "worker.py"
    w.write_text(textwrap.dedent(f"""
        import sys
        if "--resume" not in sys.argv:
            sys.exit(3)
        open({str(marker)!r}, "w").write("ok")
    """))
    events = telemetry.JsonlWriter(str(tmp_path / "ev"))
    sleeps = []
    msgs = []
    rc = elastic.supervise(
        [str(w)], max_restarts=2, poll_interval=0.05,
        backoff_base_s=0.5, rng=lambda: 1.0, _sleep=sleeps.append,
        events=events, _print=msgs.append,
    )
    assert rc == 0 and marker.exists()
    assert sleeps == [0.5]  # attempt 1, full-jitter upper envelope
    assert any("after 0.50s backoff" in m for m in msgs)
    events.close()
    evs = telemetry.read_events(events.path)  # read_events validates
    restarts = [e for e in evs if e["name"] == "elastic.restart"]
    assert len(restarts) == 1
    assert restarts[0]["attrs"]["attempt"] == 1
    assert restarts[0]["attrs"]["backoff_s"] == 0.5
    assert restarts[0]["attrs"]["reason"] == "rc=3"


def test_supervise_windowed_breaker_gives_up(tmp_path):
    w = tmp_path / "crash.py"
    w.write_text("raise SystemExit(7)")
    msgs = []
    rc = elastic.supervise(
        [str(w)], max_restarts=2, restart_window_s=300.0,
        resume_arg=None, poll_interval=0.05, backoff_base_s=0.0,
        _print=msgs.append,
    )
    assert rc == 7
    assert any("within 300s" in m for m in msgs)


# -- the straggler chaos drill (ISSUE 10) -------------------------------------


def test_fleet_chaos_delay_drill_flags_straggler(tmp_path):
    """ISSUE 10 satellite: 2 real replica workers under router load, the
    chaos ``delay`` action slows r1's serving path mid-run — r1 stays
    HEALTHY (keeps serving, /healthz green, nothing restarts it), and
    only the federation-side skew scoring names it:
    ``fleet_replica_skew{replica="r1"}`` over the straggler factor, the
    ``replica_straggler`` advisory page firing on the aggregator's
    /alertz with a transition naming r1, and the router's fleet latency
    histogram carrying exemplar trace ids for the slow bucket."""
    from mpi4dl_tpu.fleet.chaos import inject, parse_chaos_spec
    from mpi4dl_tpu.fleet.replica import ReplicaProcess, worker_cmd
    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.telemetry.federation import FederatedAggregator

    tele = str(tmp_path / "tele")
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    procs = [
        ReplicaProcess(
            f"r{i}",
            worker_cmd(["--image-size", "16", "--max-batch", "2",
                        "--telemetry-dir", tele]),
            base_dir=str(tmp_path / "fleet"),
            env=env,
            log_path=str(tmp_path / f"r{i}.log"),
        )
        for i in range(2)
    ]
    router = Router(
        example_shape=(16, 16, 3), inflight_per_replica=4,
        health_interval_s=0.1, telemetry_dir=tele,
    )
    agg = None
    try:
        for p in procs:
            p.spawn()
        ports = [p.wait_ready(timeout_s=420.0) for p in procs]
        for p, pp in zip(procs, ports):
            router.add_replica(
                p.name,
                f"http://127.0.0.1:{pp['predict_port']}",
                f"http://127.0.0.1:{pp['metrics_port']}",
            )
        agg = FederatedAggregator(
            replicas={
                p.name: f"http://127.0.0.1:{pp['metrics_port']}"
                for p, pp in zip(procs, ports)
            },
            straggler_factor=4.0, straggler_min_count=20,
        )
        x = np.zeros((16, 16, 3), np.float32)

        # Phase 1 — healthy baseline: both replicas serve, nobody skews.
        rep = run_closed_loop(router, 80, concurrency=8, deadline_s=60.0)
        assert rep["served"] == 80 and rep["errors"] == 0
        agg.scrape_once()
        assert agg.straggler_alert.state == "inactive"

        # Phase 2 — inject the delay through the real chaos plumbing
        # (spec grammar → /chaos → delay_predict), via a stub supervisor
        # exposing slot_by_index like the CLI's.
        class _Slots:
            def slot_by_index(self, i):
                import types

                p = procs[i]
                return types.SimpleNamespace(
                    name=p.name, pid=p.pid,
                    client=router._replicas[p.name].client,
                )

        # 1 s/batch: far above the shared CPU box's own tail noise, so
        # the straggler's p99 bucket separates from the healthy
        # replica's under any load jitter.
        record = inject(parse_chaos_spec("delay:1=1"), _Slots())
        assert record["applied"] == "delay_predict"

        rep = run_closed_loop(router, 40, concurrency=8, deadline_s=60.0)
        assert rep["served"] == 40 and rep["errors"] == 0  # slow, not down
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            agg.scrape_once()
            skew = agg.last_skew.get("skew", {})
            if skew.get("r1", 0) >= 4.0:
                break
            # The delayed replica keeps absorbing a trickle (health says
            # yes), so its own histogram keeps inflating.
            run_closed_loop(router, 16, concurrency=4, deadline_s=60.0)
        skew = agg.last_skew["skew"]
        assert skew.get("r1", 0) >= 4.0, agg.last_skew
        assert skew.get("r0", 99) < 4.0, agg.last_skew

        # The gauge + the page, fleet-side.
        assert agg.registry.get("fleet_replica_skew").value(
            replica="r1"
        ) >= 4.0
        assert agg.straggler_alert.state == "firing"
        (t,) = [
            tr for tr in agg.straggler_transitions
            if tr["attrs"]["to"] == "firing"
        ]
        assert t["attrs"]["replica"] == "r1"
        srv = agg.serve(port=0)
        import urllib.request

        alertz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/alertz", timeout=10
        ).read())
        assert any(
            a["name"] == "replica_straggler" and a["state"] == "firing"
            for a in alertz["alerts"]
        )

        # The straggler is HEALTHY the whole time — this failure shape
        # is invisible to every liveness signal the stack had before.
        assert router._replicas["r1"].healthy
        assert procs[1].alive()

        # Router-side: the fleet histogram carries exemplars, and the
        # slow bucket's exemplar is a real trace id (the analyze-tail
        # entry point).
        (series,) = router.registry.get(
            "fleet_request_latency_seconds"
        ).snapshot_series()
        assert series["exemplars"]
        worst = max(
            series["exemplars"].values(), key=lambda e: e["value"]
        )
        assert worst["value"] >= 1.0  # a delayed request tops the map
        # The exemplar is a real loadgen-minted id ("client-<pid>-...");
        # the router only mints its own ("fleet-...") for callers that
        # pass none.
        assert worst["trace_id"].startswith(("client-", "fleet-"))
        assert len(worst["trace_id"].split("-")) == 4
    finally:
        if agg is not None:
            agg.close()
        router.stop(drain=False)
        for p in procs:
            p.terminate(wait_s=10.0)


# -- the tier-1 chaos drill ---------------------------------------------------


def _drill_events(tele_dir) -> "list[dict]":
    events = []
    for f in sorted(os.listdir(tele_dir)):
        if f.endswith(".jsonl"):
            events.extend(
                telemetry.read_events(os.path.join(tele_dir, str(f)))
            )
    return events


def test_fleet_ha_drill_kill_router_mid_flight(tmp_path):
    """ISSUE 12 acceptance: 2 front-door router processes × 2 real
    replicas under closed-loop load, ``kill -9`` one ROUTER mid-flight.
    Every future resolves with a result (the client fails over —
    router_failovers > 0), the supervisor respawns the router slot, the
    successor replays its predecessor's journal
    (fleet_router_journal_replays_total > 0 on its /metrics), and no
    trace id is served twice across all engine logs."""
    import urllib.request

    from mpi4dl_tpu.fleet.frontdoor import RouterSetClient
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    tele = str(tmp_path / "tele")
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    sup = FleetSupervisor(
        ["--image-size", "16", "--max-batch", "2",
         "--telemetry-dir", tele],
        router=None,
        routers=2,
        router_args=["--image-size", "16", "--max-attempts", "4",
                     "--inflight-per-replica", "4",
                     "--health-interval", "0.1",
                     "--replay-grace", "1.0",
                     "--telemetry-dir", tele],
        replicas=2, max_replicas=2,
        env=env,
        base_dir=str(tmp_path / "fleet"),
        reconcile_interval_s=0.1,
        heartbeat_timeout_s=5.0,
        backoff_base_s=0.1, backoff_max_s=0.5,
        spawn_timeout_s=420.0,
    )
    n_requests = 300
    client = None
    try:
        sup.start()
        sup.wait_ready(timeout_s=420)
        client = RouterSetClient(
            sup.router_submit_urls(), example_shape=(16, 16, 3),
            default_deadline_s=120.0, telemetry_dir=tele,
            down_s=0.3, backoff_base_s=0.02, backoff_max_s=0.2,
        )
        report = {}

        def load():
            report.update(run_closed_loop(
                client, n_requests, concurrency=8, deadline_s=120.0,
                events=client.events,
            ))

        t = threading.Thread(target=load)
        t.start()
        # Mid-flight: wait for real traffic THROUGH the victim router,
        # then SIGKILL it while requests sit in its queue + RPCs.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            s = client.stats()
            if s["per_router"].get("rt1", {}).get("dispatches", 0) >= 20:
                break
            time.sleep(0.01)
        victim = sup.router_slot_by_index(1)
        victim_pid = victim.pid
        os.kill(victim_pid, signal.SIGKILL)
        t.join(timeout=300)
        assert not t.is_alive(), "load run wedged"

        # Zero accepted-request loss through a ROUTER death: every
        # future resolved with a RESULT, absorbed by client failover.
        assert report["served"] == n_requests, report
        assert report["errors"] == 0 and report["deadline_misses"] == 0
        assert report["router_failovers"] >= 1, report

        # The supervisor restores the router set; the successor is a
        # fresh pid on the same slot (same name, same journal).
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            if (
                sup.running_router_count() == 2
                and sup.router_slot_by_index(1).pid != victim_pid
            ):
                break
            time.sleep(0.2)
        assert sup.running_router_count() == 2, sup.state()
        assert sup.router_slot_by_index(1).pid != victim_pid
        assert sup.last_router_recovery_s is not None

        # The successor replayed the predecessor's journal: the killed
        # router had accepted-but-uncompleted entries (in-flight RPCs
        # died with its sockets), and every one of them was processed —
        # deduped against replica-reported completions or re-dispatched
        # with a fresh epoch.
        replay_deadline = time.monotonic() + 60
        total = 0
        while time.monotonic() < replay_deadline:
            port = sup.router_slot_by_index(1).ports["metrics_port"]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/snapshotz", timeout=10
            ) as resp:
                snap = json.loads(resp.read().decode())
            series = snap["metrics"].get(
                "fleet_router_journal_replays_total", {}
            ).get("series", [])
            total = sum(s["value"] for s in series)
            if total > 0:
                break
            time.sleep(0.5)
        assert total > 0, "successor never replayed the journal"
    finally:
        sup.close()
        if client is not None:
            client.close()

    # Postmortem over the flushed logs: across every replica engine's
    # span log, no trace id was SERVED twice — the client's failover
    # retries and the successor's replay both deduped against the
    # replicas' idempotency caches instead of re-executing.
    events = _drill_events(tele)
    served_by_tid: "dict[str, int]" = {}
    for e in events:
        if (
            e.get("kind") == "span" and e.get("name") == "serve.request"
            and e["attrs"].get("outcome") == "served"
        ):
            served_by_tid[e["trace_id"]] = (
                served_by_tid.get(e["trace_id"], 0) + 1
            )
    assert served_by_tid, "no engine spans flushed"
    doubles = {t: n for t, n in served_by_tid.items() if n > 1}
    assert not doubles, f"double-served trace ids: {doubles}"


#: The shared drill fleet's worker-side sentinel cadence (seconds) —
#: the corrupt drill's detection clock.
CANARY_INTERVAL_S = 0.25


@pytest.fixture(scope="module")
def live_fleet(tmp_path_factory):
    """One real 2-replica fleet shared by the corrupt and kill drills —
    a full spawn + warm-up costs real seconds of the tier-1 budget on
    the shared CPU box, and the two drills exercise disjoint failure
    paths on the same topology. Workers run the numerics sentinel hot
    (``--canary-interval 0.25``) so corruption is detected within one
    interval; the kill drill is indifferent to canaries (outcome
    ``canary`` never touches a client book). ``reconcile_interval_s``
    is a shade slower than the plain kill drill used to run so the
    fence → quarantine window stays observable to a fast scraper.

    The kill drill — the LAST test in this file — calls ``close()``
    itself before its flushed-log postmortem; teardown is a guarded
    no-op after that."""
    import types

    from mpi4dl_tpu.telemetry.autoscale import AutoscaleConfig

    base = tmp_path_factory.mktemp("fleet_drills")
    tele = str(base / "tele")
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    router = Router(
        example_shape=(16, 16, 3), max_attempts=4,
        inflight_per_replica=4, health_interval_s=0.1,
        telemetry_dir=tele,
    )
    sup = FleetSupervisor(
        ["--image-size", "16", "--max-batch", "2",
         "--telemetry-dir", tele,
         "--canary-interval", str(CANARY_INTERVAL_S)],
        router=router,
        replicas=2, max_replicas=2,
        federation=telemetry.SLOConfig(
            availability=0.999, interval_s=0.5,
            autoscale=AutoscaleConfig(min_replicas=2, max_replicas=2),
        ),
        env=env,
        base_dir=str(base / "fleet"),
        reconcile_interval_s=0.25,
        heartbeat_timeout_s=5.0,
        backoff_base_s=0.1, backoff_max_s=0.5,
        spawn_timeout_s=420.0,
    )
    closed = []

    def close():
        if closed:  # guard: the kill drill closes early for its
            return  # postmortem; double Router.stop() is not safe
        closed.append(True)
        sup.close()
        router.stop(drain=False)

    fleet = types.SimpleNamespace(router=router, sup=sup, tele=tele,
                                  close=close)
    try:
        sup.start()
        sup.wait_ready(timeout_s=420)
        yield fleet
    finally:
        close()


def test_fleet_corrupt_drill_detect_page_quarantine(live_fleet):
    """ISSUE 19 acceptance (the numerics drill): flip exponent bits in
    one live replica's param buffer through the real chaos plumbing
    (``corrupt:1`` → /chaos → ``corrupt_params``) while 300 client
    futures are in flight. The victim's own sentinel detects within
    ~one canary interval (a schema-valid ``canary.failure`` on its
    JSONL log), the federation page names it (``numerics_divergence``
    firing on /alertz with r1's evidence), the supervisor quarantines
    it (drain → kill → respawn under ``reason="numerics"``), and the
    survivor keeps every client whole: 300/300 resolve, zero errors,
    zero deadline misses."""
    import urllib.request

    from mpi4dl_tpu.fleet.chaos import inject, parse_chaos_spec
    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.telemetry.federation import FederatedAggregator

    router, sup, tele = live_fleet.router, live_fleet.sup, live_fleet.tele

    # Fleet-side view: our own aggregator on a hot scrape loop. The
    # fence → kill window is about one reconcile tick, so a slow
    # scraper could miss the live fenced payload entirely — and a
    # failed scrape keeps the replica's LAST snapshot, so one caught
    # glimpse persists through the victim's dead window.
    agg = FederatedAggregator(replicas={
        s.name: f"http://127.0.0.1:{s.ports['metrics_port']}"
        for s in (sup.slot_by_index(0), sup.slot_by_index(1))
    }, events=telemetry.JsonlWriter(tele, filename="incidents-corrupt.jsonl"))
    stop_scrape = threading.Event()

    def scrape_loop():
        while not stop_scrape.is_set():
            agg.scrape_once()
            time.sleep(0.02)

    scraper = threading.Thread(target=scrape_loop)

    n_requests = 300
    base_served = router.stats()["served"]
    report = {}

    def load():
        report.update(run_closed_loop(
            router, n_requests, concurrency=8, deadline_s=120.0,
            events=router.events,
        ))

    t = threading.Thread(target=load)
    try:
        scraper.start()
        t.start()
        # Mid-flight: wait for real traffic, then corrupt r1's live
        # param buffer while requests are queued on it.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if router.stats()["served"] >= base_served + 60:
                break
            time.sleep(0.01)
        victim_pid = sup.slot_by_index(1).pid
        t_inject = time.time()
        record = inject(parse_chaos_spec("corrupt:1"), sup)
        assert record["applied"] == "corrupt_params"
        assert record["forensics"]["bits"] == 3  # grammar default
        assert record["forensics"]["leaf"]

        # The page: r1's self-report (fence latch, canary failures,
        # checksum drift) crosses the ≥1.0 score threshold and the
        # transition names the suspect with its evidence.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if agg.numerics_alert.state == "firing":
                break
            time.sleep(0.02)
        assert agg.numerics_alert.state == "firing", agg.last_numerics
        assert agg.last_numerics["score"].get("r1", 0) >= 1.0
        firing = [
            tr for tr in agg.numerics_transitions
            if tr["attrs"]["to"] == "firing"
        ]
        assert firing and firing[0]["attrs"]["replica"] == "r1"
        assert firing[0]["attrs"]["evidence"]
        assert agg.registry.get("fleet_numerics_skew").value(
            replica="r1"
        ) >= 1.0
        srv = agg.serve(port=0)
        alertz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/alertz", timeout=10
        ).read())
        assert any(
            a["name"] == "numerics_divergence" and a["state"] == "firing"
            for a in alertz["alerts"]
        )

        # Quarantine: routers stop pulling, the victim dies, a clean
        # successor spawns on the same slot under the distinct
        # reason="numerics" restart label (repeat offenders would trip
        # the same RestartBreaker as any crash loop).
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            if (
                sup.running_count() == 2
                and sup.slot_by_index(1).pid != victim_pid
            ):
                break
            time.sleep(0.2)
        assert sup.running_count() == 2, sup.state()
        assert sup.slot_by_index(1).pid != victim_pid
        assert sup.registry.get("fleet_replica_restarts_total").value(
            replica="r1", reason="numerics"
        ) >= 1

        t.join(timeout=300)
        assert not t.is_alive(), "load run wedged"
        # The survivor kept every client whole through the quarantine.
        assert report["served"] == n_requests, report
        assert report["errors"] == 0 and report["deadline_misses"] == 0

        # -- incident engine (ISSUE 20): the drill is SCORED. The
        # numerics page opened exactly ONE incident on this
        # aggregator's manager; the quarantine kill's availability page
        # FOLDS into it rather than opening a second one.
        inc_mgr = agg.incidents
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if inc_mgr.opened_total >= 1:
                break
            time.sleep(0.02)
        assert inc_mgr.opened_total == 1, inc_mgr.state()

        # chaos.injected self-label golden: the injection is on the
        # fleet event log as a schema-valid event naming op + victim —
        # the postmortem blames the drill from the log alone.
        chaos_evs = [
            e for e in _drill_events(tele)
            if e.get("name") == "chaos.injected" and e["ts"] >= t_inject - 1
        ]
        assert len(chaos_evs) == 1, chaos_evs
        cev = telemetry.validate_event(chaos_evs[0])
        assert cev["attrs"]["op"].startswith("corrupt:r1")
        assert cev["attrs"]["action"] == "corrupt"
        assert cev["attrs"]["domain"] == "replica"
        assert cev["attrs"]["target"] == "r1"
        assert cev["attrs"]["pid"] == victim_pid

        # Close: swap the r1 target to the clean successor (the same
        # swap the supervisor-integrated aggregator performs on
        # confirmed death + handshake) — the next clean scrape resolves
        # both pages and the incident closes.
        agg.add_replica(
            "r1",
            f"http://127.0.0.1:"
            f"{sup.slot_by_index(1).ports['metrics_port']}",
        )
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if inc_mgr.closed_total >= 1:
                break
            time.sleep(0.02)
        assert inc_mgr.closed_total == 1, inc_mgr.state()
        assert inc_mgr.open_incident is None
        assert inc_mgr.opened_total == 1  # folded, never fragmented

        # /incidentz on the aggregator's MetricsServer: the postmortem
        # names the injected op as first cause, and the drill's own
        # page is a member.
        incidentz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/incidentz", timeout=10
        ).read())
        assert incidentz["counts"] == {"opened": 1, "closed": 1}
        live_pm = incidentz["closed"][-1]
        assert "numerics_divergence" in live_pm["incident"]["members"]
        cause = live_pm["first_cause"]
        assert cause["event"] == "chaos.injected", live_pm["timeline"]
        assert cause["attrs"]["op"].startswith("corrupt:r1")
        assert cause["label"] == f"injected chaos op {cev['attrs']['op']}"
        assert agg.registry.get("incidents_total").value(
            state="opened"
        ) == 1
        assert agg.registry.get("incident_open").value() == 0.0

        # Offline reconstruction (the analyze-incident path) over the
        # same logs matches the live /incidentz timeline event for
        # event — closed windows are bounded by closed_ts, so the
        # still-running fleet cannot skew the comparison.
        from mpi4dl_tpu.telemetry.incident import (
            build_postmortem, collect_events, reconstruct_incidents,
        )
        events = collect_events([tele])
        recs = [
            r for r in reconstruct_incidents(events)
            if r["id"] == live_pm["incident"]["id"]
        ]
        assert len(recs) == 1
        off_pm = build_postmortem(recs[0], events)
        assert off_pm["timeline"] == live_pm["timeline"]
        assert off_pm["first_cause"] == cause
    finally:
        stop_scrape.set()
        scraper.join(timeout=10)
        agg.close()
        t.join(timeout=300)

    # Detection latency: the victim's sentinel audits the params
    # checksum every tick, so the canary.failure lands within ~one
    # canary interval of the injection (generous slop for the shared
    # CPU box). Event-kind records flush the writer's whole backlog
    # immediately, so the paper trail is on disk despite the SIGKILL.
    fails = [
        e for e in _drill_events(tele)
        if e.get("name") == "canary.failure" and e["ts"] >= t_inject - 1
    ]
    assert fails, "no canary.failure event on the victim's log"
    first_ts = min(e["ts"] for e in fails)
    assert first_ts - t_inject <= CANARY_INTERVAL_S + 10.0
    assert any(e["attrs"]["check"] == "params_checksum" for e in fails)

    # No post-detection answers from the corrupted replica: in ITS log
    # (the file holding the canary.failure), nothing was engine-served
    # past the fence beyond the in-flight residue the worker 503'd.
    # Best-effort by construction — the SIGKILL truncates the span
    # tail, but the failure event's forced flush pushed out everything
    # buffered before the fence.
    for f in sorted(os.listdir(tele)):
        if not f.endswith(".jsonl"):
            continue
        evs = telemetry.read_events(os.path.join(tele, str(f)))
        fts = [
            e["ts"] for e in evs
            if e.get("name") == "canary.failure" and e["ts"] >= t_inject - 1
        ]
        if not fts:
            continue
        late = [
            e for e in evs
            if e.get("kind") == "span" and e.get("name") == "serve.request"
            and e["attrs"].get("outcome") == "served"
            and e["ts"] > min(fts) + 2.0
        ]
        assert not late, f"victim served after its fence: {late[:3]}"


def test_fleet_chaos_drill_kill_replica_mid_flight(live_fleet):
    """ISSUE acceptance: 2 replicas under closed-loop load, kill -9 one
    mid-flight. Zero accepted-request loss (every future resolves with a
    result), no request served twice, the survivor absorbs the requeue,
    the supervisor restores the fleet to the (federated)
    autoscale_desired_replicas count, and one requeued request's trace
    joins client → router → dead replica → survivor.

    Runs on the shared drill fleet AFTER the corrupt drill, so counter
    asserts are written against deltas/cumulative values and the log
    postmortem is bounded to this drill's time window.

    ISSUE 20 additions: the kill goes through the chaos plumbing
    (``inject("kill:1")`` → a ``chaos.injected`` self-label on the
    fleet log), a fresh aggregator + incident manager scores the drill
    — exactly one incident, availability page as member, the injected
    op named first cause — and after the fleet is torn down the
    offline ``analyze incident`` CLI reconstructs the same timeline
    from the logs alone, event for event."""
    import urllib.request

    from mpi4dl_tpu.fleet.chaos import inject, parse_chaos_spec
    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.telemetry.federation import FederatedAggregator

    router, sup, tele = live_fleet.router, live_fleet.sup, live_fleet.tele
    n_requests = 400
    t_floor = time.time()  # postmortem window: this drill only
    # The drill's scorer: its own aggregator + incident manager (the
    # corrupt drill's was closed with its test). The evidence floor
    # pins this incident's window to THIS drill — the corrupt drill's
    # chaos op, minutes old on the same log, must not be re-blamed.
    agg = FederatedAggregator(replicas={
        s.name: f"http://127.0.0.1:{s.ports['metrics_port']}"
        for s in (sup.slot_by_index(0), sup.slot_by_index(1))
    }, events=telemetry.JsonlWriter(tele, filename="incidents-kill.jsonl"))
    agg.incidents.evidence_floor_ts = t_floor
    stop_scrape = threading.Event()

    def scrape_loop():
        while not stop_scrape.is_set():
            agg.scrape_once()
            time.sleep(0.02)

    scraper = threading.Thread(target=scrape_loop)
    live_pm = None
    try:
        scraper.start()
        base_served = router.stats()["served"]

        report = {}

        def load():
            report.update(run_closed_loop(
                router, n_requests, concurrency=8, deadline_s=120.0,
                events=router.events,
            ))

        t = threading.Thread(target=load)
        t.start()
        # Deterministic mid-flight kill: wait for real traffic, then
        # kill -9 replica 1 (via the chaos plumbing, so the injection
        # self-labels on the log) while requests are in flight.
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if router.stats()["served"] >= base_served + 40:
                break
            time.sleep(0.01)
        victim = sup.slot_by_index(1)
        victim_pid = victim.pid
        record = inject(parse_chaos_spec("kill:1"), sup)
        assert record["pid"] == victim_pid
        assert record["replica"] == "r1"
        t.join(timeout=300)
        assert not t.is_alive(), "load run wedged"

        # Zero accepted-request loss: every submitted future resolved,
        # with a RESULT (the survivor absorbed the requeue).
        assert report["served"] == n_requests, report
        assert report["errors"] == 0 and report["deadline_misses"] == 0
        stats = router.stats()
        assert stats["requeued"] >= 1  # the ledger moved to the survivor
        assert router.registry.get("fleet_requeues_total").value(
            reason="replica_removed"
        ) or router.registry.get("fleet_requeues_total").value(
            reason="dispatch_error"
        )

        # Supervisor restores the fleet to the federated desired count.
        assert sup.desired_replicas() == 2
        deadline = time.monotonic() + 420
        while time.monotonic() < deadline:
            if sup.running_count() == 2:
                break
            time.sleep(0.2)
        assert sup.running_count() == 2, sup.state()
        assert sup.restarts >= 1
        assert sup.slot_by_index(1).pid != victim_pid
        assert sup.last_recovery_s is not None
        assert sup.registry.get("fleet_replica_restarts_total").value(
            replica="r1", reason="exit"
        ) >= 1

        # -- incident engine: the availability page opened exactly one
        # incident; swap the dead target to the respawned replica and
        # the page resolves → the incident closes with its postmortem.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if agg.incidents.opened_total >= 1:
                break
            time.sleep(0.02)
        assert agg.incidents.opened_total == 1, agg.incidents.state()
        agg.add_replica(
            "r1",
            f"http://127.0.0.1:"
            f"{sup.slot_by_index(1).ports['metrics_port']}",
        )
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if agg.incidents.closed_total >= 1:
                break
            time.sleep(0.02)
        assert agg.incidents.closed_total == 1, agg.incidents.state()
        assert agg.incidents.opened_total == 1

        srv = agg.serve(port=0)
        incidentz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/incidentz", timeout=10
        ).read())
        assert incidentz["counts"] == {"opened": 1, "closed": 1}
        live_pm = incidentz["closed"][-1]
        assert "replica_unreachable" in live_pm["incident"]["members"]
        cause = live_pm["first_cause"]
        assert cause["event"] == "chaos.injected", live_pm["timeline"]
        assert cause["attrs"]["op"].startswith("kill:r1")
        assert cause["attrs"]["pid"] == victim_pid
        # The floor did its job: the corrupt drill's earlier op is off
        # this timeline entirely.
        chaos_on_tl = [
            e for e in live_pm["timeline"] if e["name"] == "chaos.injected"
        ]
        assert len(chaos_on_tl) == 1
    finally:
        stop_scrape.set()
        scraper.join(timeout=10)
        agg.close()
        live_fleet.close()

    # Postmortem over the flushed logs (workers SIGTERMed + router
    # stopped above, so every writer closed/flushed). Bounded to this
    # drill's window: the corrupt drill shares the telemetry dir, and
    # its fence deliberately 503s answers the engine already computed —
    # those traces are requeued and legally served again elsewhere.
    events = [e for e in _drill_events(tele) if e["ts"] >= t_floor]
    # No double execution: across every replica's engine log, no trace
    # id was SERVED twice.
    served_by_tid: "dict[str, int]" = {}
    for e in events:
        if (
            e.get("kind") == "span" and e.get("name") == "serve.request"
            and e["attrs"].get("outcome") == "served"
        ):
            served_by_tid[e["trace_id"]] = (
                served_by_tid.get(e["trace_id"], 0) + 1
            )
    doubles = {t: n for t, n in served_by_tid.items() if n > 1}
    assert not doubles, f"double-served trace ids: {doubles}"

    # One requeued request's full lifetime joins under a single id:
    # client segment, the router's dead-replica attempt, the survivor's
    # engine spans.
    groups = telemetry.group_spans_by_trace(events)
    joined = None
    for tid, evs in groups.items():
        disp = [e for e in evs if e["name"] == "router.dispatch"]
        replicas = {e["attrs"]["replica"] for e in disp}
        if len(replicas) > 1 and any(
            e["attrs"]["outcome"] != "ok" for e in disp
        ):
            names = {e["name"] for e in evs}
            if {"client.request", "router.request",
                    "serve.request"} <= names:
                joined = tid
                break
    assert joined is not None, "no requeued trace joined all three hops"
    doc = telemetry.chrome_trace(events, trace_id=joined)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    span_names = {e["name"] for e in xs}
    assert any(n.startswith("rpc_") for n in span_names)  # both hops
    assert {"queue_wait", "device_compute"} <= span_names  # survivor

    # Offline auto-postmortem: with the fleet GONE, the analyze CLI
    # rebuilds both drills' incidents from the logs alone, and the kill
    # incident's timeline matches what /incidentz served live, event
    # for event (same pure builders over the same flushed files).
    import subprocess
    r = subprocess.run(
        [sys.executable, "-m", "mpi4dl_tpu.analyze", "incident",
         tele, "--json"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH":
             REPO + os.pathsep + os.environ.get("PYTHONPATH", "")},
    )
    assert r.returncode == 0, r.stderr
    postmortems = json.loads(r.stdout)
    assert len(postmortems) == 2  # corrupt drill's + this one
    off_pm = [
        p for p in postmortems
        if p["incident"]["id"] == live_pm["incident"]["id"]
    ]
    assert len(off_pm) == 1
    off_pm = off_pm[0]
    assert off_pm["timeline"] == live_pm["timeline"]
    assert off_pm["first_cause"] == live_pm["first_cause"]
    assert off_pm["incident"]["mttr_s"] == pytest.approx(
        live_pm["incident"]["mttr_s"]
    )
    # Blame accuracy across the drill set: every reconstructed incident
    # names ITS injected chaos op — corrupt blamed corrupt, kill kill.
    blamed = sorted(
        p["first_cause"]["attrs"]["op"].split(":")[0] for p in postmortems
    )
    assert blamed == ["corrupt", "kill"]
    assert len({e["pid"] for e in xs}) >= 2  # client+router pid, engine pid
