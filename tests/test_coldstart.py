"""ISSUE 18: cold-start observability — executable fingerprints, the
compile-time ledger, and recovery phase attribution.

The tentpole claims pinned here:

- ``executable_fingerprint`` is a CONTENT key: stable across separate
  processes for the same config (the property that makes it usable as a
  fleet-wide compile-cache key), distinct under any config change that
  produces a different executable (px, bucket, dtype, mesh shape,
  shardings), and insensitive to the non-semantic decoration (source
  paths in ``metadata={...}``/``loc(...)``) that varies per checkout.
- ``FootprintLedger`` times the trace/compile split at record time,
  carries the fingerprint next to the predicted peak, merges the
  first-execute ``warm_s`` via ``annotate``, and accumulates every phase
  into the cataloged ``compile_seconds{program, phase}`` gauge —
  except ``rollup`` aggregates, which must not double-count.
- ``recovery_phase_decomposition`` always emits the full fixed phase
  vocabulary, sums exactly to the supervisor's recovery wall (spawn is
  the clamped residual), and drops unknown keys.
- ``enable_compilation_cache`` stops failing silent: the gate publishes
  ``compile_cache_enabled`` 0 with the versioned reason.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.telemetry import MetricsRegistry
from mpi4dl_tpu.telemetry.coldstart import (
    RECOVERY_PHASES,
    canonicalize_hlo,
    executable_fingerprint,
    publish_cache_status,
    recovery_phase_decomposition,
)
from mpi4dl_tpu.telemetry.memory import FootprintLedger

# ---------------------------------------------------------------------------
# canonicalization + fingerprint units


def test_canonicalize_strips_nonsemantic_decoration():
    a = canonicalize_hlo(
        'HloModule m, metadata={op_name="jit_f" source_file="/home/a/f.py"}\n'
        '  ROOT %r = f32[2] add(%a, %b) loc("/home/a/f.py":10)\n'
        '#loc1 = loc("/home/a/f.py":10:2)\n'
    )
    b = canonicalize_hlo(
        'HloModule m\n  ROOT %r = f32[2] add(%a, %b)\n'
    )
    assert a == b
    # Real opcode text survives — canonicalization is not a no-op hash.
    assert "add" in a and "metadata" not in a and "#loc" not in a


def test_fingerprint_shape_and_determinism():
    fp = executable_fingerprint("HloModule m", backend="cpu")
    assert fp.startswith("xf") and len(fp) == 18
    assert fp == executable_fingerprint("HloModule m", backend="cpu")


def test_fingerprint_distinct_per_config_axis():
    base = dict(
        backend="tpu", mesh_shape=(2, 2), in_shardings=("P(None)",),
        out_shardings=("P('data')",), donated=(0,),
        jax_version="0.4.37", jaxlib_version="0.4.36",
    )
    ref = executable_fingerprint("HloModule m", **base)
    for axis, value in [
        ("backend", "cpu"),
        ("mesh_shape", (1, 4)),          # same forward, different grid
        ("in_shardings", ("P('sp')",)),
        ("out_shardings", ("P(None)",)),
        ("donated", ()),
        ("jax_version", "0.5.0"),        # a jax upgrade invalidates keys
    ]:
        perturbed = executable_fingerprint(
            "HloModule m", **{**base, axis: value}
        )
        assert perturbed != ref, f"fingerprint blind to {axis}"
    assert executable_fingerprint("HloModule other", **base) != ref


# ---------------------------------------------------------------------------
# process stability (satellite b): the content-key property

_FP_SCRIPT = r"""
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax, jax.numpy as jnp
from mpi4dl_tpu.evaluate import aot_compile_predict, collect_batch_stats
from mpi4dl_tpu.models.resnet import get_resnet_v2
from mpi4dl_tpu.parallel.partition import init_cells
from mpi4dl_tpu.utils import get_depth


def fingerprints(size, buckets, dtype):
    cells = get_resnet_v2(depth=get_depth(2, 1), num_classes=10,
                          pool_kernel=size // 4)
    params = init_cells(cells, jax.random.PRNGKey(0),
                        jnp.zeros((1, size, size, 3)))
    stats = collect_batch_stats(
        cells, params, [jnp.zeros((2, size, size, 3), jnp.float32)]
    )
    timings = {}
    aot_compile_predict(cells, params, stats, (size, size, 3),
                        buckets=buckets, dtype=dtype, timings=timings)
    return {str(b): t["fingerprint"] for b, t in timings.items()}


out = {"base": fingerprints(16, (1, 2), jnp.float32)}
if "--perturb" in sys.argv:
    out["px24"] = fingerprints(24, (1,), jnp.float32)
    out["bf16"] = fingerprints(16, (1,), jnp.bfloat16)
print(json.dumps(out))
"""


def _fp_run(tmp_path, *args):
    script = tmp_path / "fp.py"
    script.write_text(_FP_SCRIPT)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run(
        [sys.executable, str(script), *args],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, r.stderr
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_fingerprint_stable_across_processes(tmp_path):
    """Two separate interpreters, same config → identical fingerprints
    (a respawning worker can look up the fleet's artifact store before
    paying the compile); px / bucket / dtype perturbations → distinct."""
    run1 = _fp_run(tmp_path, "--perturb")
    run2 = _fp_run(tmp_path)
    assert run1["base"] == run2["base"]  # the content-key property
    base = run1["base"]
    assert base["1"] != base["2"]            # bucket changes the executable
    assert run1["px24"]["1"] != base["1"]    # px changes the executable
    assert run1["bf16"]["1"] != base["1"]    # dtype changes the executable
    for fp in base.values():
        assert fp.startswith("xf") and len(fp) == 18


# ---------------------------------------------------------------------------
# ledger: timed record, annotate, gauge accumulation


def test_record_lowered_times_and_fingerprints():
    reg = MetricsRegistry()
    ledger = FootprintLedger(registry=reg)
    fn = jax.jit(lambda x: x * 2.0 + 1.0)
    x = jnp.zeros((4,), jnp.float32)
    entry = ledger.record_lowered("toy", fn, x)
    assert entry["program"] == "toy"
    assert entry["trace_s"] >= 0 and entry["compile_s"] > 0
    assert entry["fingerprint"].startswith("xf")
    g = reg.get("compile_seconds")
    assert g.value(program="toy", phase="compile") == pytest.approx(
        entry["compile_s"]
    )
    assert g.value(program="toy", phase="trace") == pytest.approx(
        entry["trace_s"]
    )
    # warm_s arrives late (first-execute, engine zeros run) via annotate
    # and accumulates into the same series.
    merged = ledger.annotate("toy", warm_s=0.25)
    assert merged["warm_s"] == 0.25 and merged["fingerprint"] == \
        entry["fingerprint"]
    assert g.value(program="toy", phase="warm") == 0.25
    # Unknown key: explicit no-op, nothing published.
    assert ledger.annotate("nope", warm_s=1.0) is None
    assert g.value(program="nope", phase="warm") == 0.0


def test_rollup_entries_do_not_double_count():
    """The tiled engine's per-image-bucket aggregate sums seconds the
    serve_tiled_* entries already carry — marked rollup, it must stay
    out of the gauge."""
    reg = MetricsRegistry()
    ledger = FootprintLedger(registry=reg)
    fn = jax.jit(lambda x: x + 1.0)
    ledger.record_lowered("serve_tiled_tile", fn, jnp.zeros((2,)), bucket=2)
    fine = reg.get("compile_seconds").value(
        program="serve_tiled_tile", phase="compile"
    )
    assert fine > 0
    compiled = fn.lower(jnp.zeros((2,))).compile()
    ledger.record_compiled(
        "serve_tiled", compiled, bucket=1,
        trace_s=9.0, compile_s=9.0, rollup=True,
    )
    assert reg.get("compile_seconds").value(
        program="serve_tiled", phase="compile"
    ) == 0.0
    # The entry itself still carries the aggregate for warmup_stats().
    assert ledger.get("serve_tiled", bucket=1)["compile_s"] == 9.0


# ---------------------------------------------------------------------------
# recovery phase decomposition


def test_recovery_phases_sum_to_recovery_wall():
    worker = {"import": 2.0, "construct": 1.0, "compile": 3.5,
              "warm": 0.3, "ready": 0.2, "bogus": 99.0}
    phases = recovery_phase_decomposition(10.0, worker)
    assert tuple(phases) == RECOVERY_PHASES
    assert "bogus" not in phases
    assert phases["spawn"] == pytest.approx(3.0)
    assert sum(phases.values()) == pytest.approx(10.0)


def test_recovery_phases_promotion_and_clamp():
    # Promotion: the whole recovery is routable-again time — compile and
    # warm honestly zero, spawn zero.
    p = recovery_phase_decomposition(0.05, {"ready": 0.05})
    assert p["compile"] == 0.0 and p["warm"] == 0.0
    assert p["spawn"] == 0.0 and p["ready"] == 0.05
    # Stub workers report nothing: the whole wall lands in spawn.
    p = recovery_phase_decomposition(4.0, None)
    assert p["spawn"] == 4.0 and sum(p.values()) == pytest.approx(4.0)
    # Clock skew / over-reporting never yields a negative residual.
    p = recovery_phase_decomposition(1.0, {"compile": 5.0})
    assert p["spawn"] == 0.0


# ---------------------------------------------------------------------------
# satellite a: the cache gate stops failing silent


def test_publish_cache_status_gate_is_loud():
    reg = MetricsRegistry()
    status = publish_cache_status(reg)
    gauge = reg.get("compile_cache_enabled").value()
    if jax.__version__.split(".")[:2] < "0.5".split("."):
        assert status["enabled"] is False and gauge == 0.0
        assert jax.__version__ in status["reason"]
        assert "segfault" in status["reason"]
    else:  # pragma: no cover — future jax upgrade flips the gate
        assert status["enabled"] is bool(gauge)
