"""Test harness: simulate an 8-device TPU-like mesh on CPU.

The reference has no tests (SURVEY.md §4) — correctness there requires ≥4
real GPUs + MPI. Here every distributed schedule runs single-process on 8
virtual CPU devices, so halo/pipeline/GEMS can be validated bit-for-bit
against single-device golden models in CI.

Note: the axon TPU plugin (when present) force-sets ``jax_platforms`` via
``jax.config`` during site initialization, which overrides the
``JAX_PLATFORMS`` env var — so we must override back through ``jax.config``,
not the environment.
"""

import os

from mpi4dl_tpu.compat import set_cpu_devices

set_cpu_devices(8)  # before first backend use; shims old jax via XLA_FLAGS

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache for the suite itself: the fast tier's wall
# time is dominated by CPU XLA compiles of the golden train steps, which
# are identical from run to run. Keyed by program+platform, so correctness
# is jax's concern, not ours; a cold run warms it (~7 min), warm reruns of
# the fast tier fit the <5-minute CI window (measured — README "Testing").
# (On jax 0.4.x enable_compilation_cache is a no-op — executing a
# cache-deserialized executable on that line's multi-device CPU backend
# segfaults the process; see the function's docstring.)
from mpi4dl_tpu.utils import enable_compilation_cache

enable_compilation_cache(
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".cache", "jax-cpu-tests")
)

# Golden-parity tests compare distributed (tile-local shapes) against
# single-device (full-image) runs; the MXU-packed conv picks pack factors
# from local shapes, so the two sides could legally differ in f32
# accumulation order. Pin the suite to the stock conv impl so parity
# assertions are platform-independent; tests/test_fastconv.py opts back in
# per-test to validate the packed path itself.
os.environ["MPI4DL_TPU_CONV_IMPL"] = "xla"
