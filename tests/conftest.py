"""Test harness: simulate an 8-device TPU-like mesh on CPU.

The reference has no tests (SURVEY.md §4) — correctness there requires ≥4
real GPUs + MPI. Here every distributed schedule runs single-process on 8
virtual CPU devices, so halo/pipeline/GEMS can be validated bit-for-bit
against single-device golden models in CI.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
