"""Multi-tenant QoS: quotas, weighted-fair admission, dedupe pinning.

ISSUE tentpole coverage: the tenant spec grammar and token buckets, the
:class:`TenantAdmission` edge (shed-with-refill-hint BEFORE queue
occupancy), deficit-weighted round robin fill, the rendezvous dedupe
pin, the engine's quota edge + per-tenant telemetry, and the satellite
goldens (quota convergence, 10:1 flood fairness / Jain's index).
"""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from mpi4dl_tpu import telemetry  # noqa: E402
from mpi4dl_tpu.evaluate import collect_batch_stats  # noqa: E402
from mpi4dl_tpu.models.resnet import get_resnet_v2  # noqa: E402
from mpi4dl_tpu.parallel.partition import init_cells  # noqa: E402
from mpi4dl_tpu.serve import ServingEngine  # noqa: E402
from mpi4dl_tpu.tenancy import (  # noqa: E402
    DeficitRoundRobin,
    QuotaExceededError,
    Tenant,
    TenantAdmission,
    TokenBucket,
    parse_tenants,
    pin_order,
    pin_replica,
)
from mpi4dl_tpu.utils import get_depth  # noqa: E402

SIZE = 16


@pytest.fixture(scope="module")
def model():
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=SIZE // 4
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    cal = [jnp.asarray(rng.standard_normal((4, SIZE, SIZE, 3)), jnp.float32)]
    stats = collect_batch_stats(cells, params, cal)
    return cells, params, stats


def _engine(model, **kw):
    cells, params, stats = model
    kw.setdefault("example_shape", (SIZE, SIZE, 3))
    kw.setdefault("max_batch", 4)
    kw.setdefault("default_deadline_s", 30.0)
    return ServingEngine(cells, params, stats, **kw)


def _examples(n, seed=1):
    rng = np.random.default_rng(seed)
    return [
        rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)
        for _ in range(n)
    ]


# -- spec grammar -------------------------------------------------------------


def test_tenant_spec_grammar():
    tens = parse_tenants("bulk=200:400,tight=50:100:4@tight+batch")
    by = {t.name: t for t in tens}
    assert set(by) == {"bulk", "tight", "default"}
    assert by["bulk"].rate_rps == 200 and by["bulk"].burst == 400
    assert by["bulk"].weight == 1.0 and by["bulk"].classes == ()
    assert by["tight"].weight == 4.0
    assert by["tight"].classes == ("tight", "batch")
    # The implicit default tenant is unlimited — legacy clients land
    # there unchanged.
    assert by["default"].rate_rps is None
    # 'none' = declared-but-unlimited, weight still settable.
    (free, default) = parse_tenants("free=none:3")
    assert free.rate_rps is None and free.weight == 3.0
    # Errors are loud and name the problem.
    with pytest.raises(ValueError, match="NAME=RPS"):
        parse_tenants("bulk")
    with pytest.raises(ValueError, match="BURST"):
        parse_tenants("bulk=200")
    with pytest.raises(ValueError, match="duplicate"):
        parse_tenants("a=none,a=none")
    with pytest.raises(ValueError, match="must match"):
        parse_tenants("Bad-Name=none")
    with pytest.raises(ValueError, match="rate must be"):
        Tenant("x", rate_rps=-1)
    with pytest.raises(ValueError, match="weight must be"):
        Tenant("x", weight=0)
    # burst defaults to one second of sustained rate.
    assert Tenant("x", rate_rps=25).burst == 25.0


def test_token_bucket_refill_hint_is_exact():
    clock = [0.0]
    b = TokenBucket(rate_rps=10.0, burst=2.0, clock=lambda: clock[0])
    assert b.try_take() is None
    assert b.try_take() is None
    # Empty: the hint is the EXACT wall time until one token refills.
    hint = b.try_take()
    assert hint == pytest.approx(0.1)
    # Sleeping exactly the hint admits — the convergence contract.
    clock[0] += hint
    assert b.try_take() is None
    # Refill caps at burst, never banks beyond it.
    clock[0] += 100.0
    assert b.tokens() == pytest.approx(2.0)
    # A multi-token take hints proportionally longer.
    b.try_take(2)
    assert b.try_take(2) == pytest.approx(0.2)


def test_admission_sheds_with_hint_and_publishes_metrics():
    clock = [0.0]
    reg = telemetry.MetricsRegistry()
    adm = TenantAdmission(
        "bulk=5:2,vip=none@tight", registry=reg, clock=lambda: clock[0]
    )
    assert adm.weights() == {"bulk": 1.0, "vip": 1.0, "default": 1.0}
    # In-quota admits count; the bucket gauge tracks the level.
    adm.admit("bulk")
    adm.admit("bulk")
    assert reg.get("tenant_admitted_total").value(tenant="bulk") == 2
    assert reg.get("tenant_quota_tokens").value(tenant="bulk") == 0.0
    # Over-quota: typed shed carrying the refill hint, BEFORE any queue.
    with pytest.raises(QuotaExceededError) as ei:
        adm.admit("bulk", slo_class="batch")
    e = ei.value
    assert e.shed and e.tenant == "bulk" and e.slo_class == "batch"
    assert e.retry_after_s == pytest.approx(0.2)
    assert reg.get("tenant_quota_sheds_total").value(tenant="bulk") == 1
    # Unlimited tenants never shed; unknown names are a config bug.
    for _ in range(100):
        adm.admit("vip", slo_class="tight")
    with pytest.raises(ValueError, match="unknown tenant"):
        adm.admit("nope")
    # Class allowlist: a violation is ValueError (config), not a shed.
    with pytest.raises(ValueError, match="may not submit"):
        adm.admit("vip", slo_class="bulk")
    # None lands in the implicit default tenant.
    assert adm.admit(None).name == "default"
    st = adm.state()
    assert st["bulk"]["rate_rps"] == 5 and st["vip"]["tokens"] is None


def test_deficit_round_robin_weighted_interleave():
    d = DeficitRoundRobin({"a": 2.0, "b": 1.0})
    seq = "".join(d.pick({"a", "b"}) for _ in range(9))
    assert seq.count("a") == 6 and seq.count("b") == 3
    # No starvation: b is served within every weight-sum window.
    assert "b" in seq[:3] and "b" in seq[3:6] and "b" in seq[6:9]
    # Work-conserving: an idle tenant forfeits banked credit — a burst
    # arriving after idling gets no catch-up beyond its weight.
    d2 = DeficitRoundRobin({"a": 1.0, "b": 1.0})
    for _ in range(10):
        assert d2.pick({"a"}) == "a"
    seq2 = [d2.pick({"a", "b"}) for _ in range(10)]
    assert seq2.count("b") == 5
    with pytest.raises(ValueError, match="weights must be"):
        DeficitRoundRobin({"a": 0.0})


def test_rendezvous_pin_is_consistent_across_routers():
    names = ["r0", "r1", "r2", "r3"]
    tid = "trace-abc123"
    order = pin_order(tid, names)
    assert sorted(order) == sorted(names)
    # Every router computes the identical ranking from (trace, names) —
    # the property that lets independent routers agree on a pin with no
    # coordination.
    assert pin_order(tid, list(reversed(names))) == order
    assert pin_replica(tid, names) == order[0]
    # The head dying moves the pin to the SAME successor everywhere.
    alive = [n for n in names if n != order[0]]
    assert pin_replica(tid, alive) == order[1]
    # Different traces spread across replicas (not all on one head).
    heads = {pin_replica(f"t{i}", names) for i in range(32)}
    assert len(heads) > 1
    assert pin_replica(tid, []) is None


# -- engine integration -------------------------------------------------------


def test_engine_quota_shed_before_queue_slots(model):
    """Tentpole: over-quota floods shed at submit with the bucket's
    refill hint — no queue slot occupied, typed error, counted."""
    eng = _engine(model, tenants="capped=1:2,free=none", max_queue=64)
    xs = _examples(4)
    eng.start()
    try:
        futs = [eng.submit(x, tenant="capped") for x in xs[:2]]
        with pytest.raises(QuotaExceededError) as ei:
            eng.submit(xs[2], tenant="capped")
        assert ei.value.tenant == "capped"
        assert ei.value.retry_after_s is not None
        assert ei.value.retry_after_s > 0
        # The shed consumed NO queue capacity and other tenants are
        # untouched: free + untenanted traffic admits immediately.
        futs.append(eng.submit(xs[2], tenant="free"))
        futs.append(eng.submit(xs[3]))  # -> implicit default tenant
        for f in futs:
            f.result(timeout=60)
        with pytest.raises(ValueError, match="unknown tenant"):
            eng.submit(xs[0], tenant="ghost")
    finally:
        eng.stop()
    s = eng.stats()
    assert s["rejected_quota"] == 1
    assert s["tenancy"]["capped"]["rate_rps"] == 1
    reg = eng.registry
    assert reg.get("tenant_quota_sheds_total").value(tenant="capped") == 1
    assert reg.get("tenant_admitted_total").value(tenant="capped") == 2
    assert reg.get("tenant_admitted_total").value(tenant="free") == 1
    # Per-tenant latency forensics: the class histogram carries the
    # tenant label per series.
    by = {
        (s["labels"]["slo_class"], s["labels"]["tenant"]): s["count"]
        for s in reg.get("serve_class_latency_seconds").snapshot_series()
    }
    assert by[("default", "capped")] == 2
    assert by[("default", "free")] == 1
    assert by[("default", "default")] == 1


def test_engine_off_path_unchanged(model):
    """tenants=None is the zero-overhead path: no admission object, no
    tenancy stats block, untenanted submit unchanged."""
    eng = _engine(model)
    eng.start()
    try:
        eng.submit(_examples(1)[0]).result(timeout=60)
    finally:
        eng.stop()
    s = eng.stats()
    assert "tenancy" not in s
    assert s["rejected_quota"] == 0


def test_quota_convergence_via_refill_hint(model):
    """Satellite: a retrying client that sleeps EXACTLY retry_after_s
    (the token bucket's refill time, not the batch-cadence EMA)
    converges on the tenant's configured rate — all requests serve, and
    the run takes at least the admission-rate floor."""
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    rate, burst, n = 50.0, 4.0, 20
    eng = _engine(model, tenants=f"slow={rate:g}:{burst:g}", max_queue=64)
    eng.start()
    try:
        t0 = time.monotonic()
        rep = run_closed_loop(
            eng, n, concurrency=4, deadline_s=30.0,
            queue_full_retries=1000, tenant_mix={"slow": 1.0},
        )
        dt = time.monotonic() - t0
    finally:
        eng.stop()
    assert rep["served"] == n and rep["rejected_quota"] == 0
    # The shed/retry loop engaged (the burst alone can't carry n)...
    assert rep["quota_shed_retries"] > 0
    ten = rep["by_tenant"]["slow"]
    assert ten["served"] == n and ten["quota_shed_retries"] > 0
    # ...and the wall clock respects the bucket: n requests through a
    # rate-r bucket with burst b take >= (n - b)/r seconds (0.8 margin
    # for the final in-flight batch).
    assert dt >= 0.8 * (n - burst) / rate
    # Convergence, not thundering: the retry count stays within a small
    # multiple of the shed count a compliant client would see.
    assert rep["quota_shed_retries"] < 40 * n


def test_fairness_two_tenant_flood_golden(model):
    """Satellite golden: a 10:1 in-quota flood must not starve the
    victim — DWRR batch fill bounds the victim's p99 at <= 1.5x its
    solo p99, and weighted service stays fair (Jain's index)."""
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    def run(mix, n):
        eng = _engine(
            model, tenants="victim=none,bully=none",
            max_queue=256, max_batch=4,
        )
        eng.start()
        try:
            return run_closed_loop(
                eng, n, concurrency=16, deadline_s=60.0, tenant_mix=mix,
            )
        finally:
            eng.stop()

    solo = run({"victim": 1.0}, 24)
    flood = run({"bully": 10.0, "victim": 1.0}, 110)
    solo_p99 = solo["by_tenant"]["victim"]["latency_s"]["p99"]
    flood_p99 = flood["by_tenant"]["victim"]["latency_s"]["p99"]
    assert flood["by_tenant"]["victim"]["served"] >= 8
    # The headline golden. 1.5x is the ISSUE's bound; CPU-jitter margin
    # is already inside it because both sides run the same stack.
    assert flood_p99 <= 1.5 * max(solo_p99, 0.05), (
        f"victim p99 {flood_p99:.3f}s vs solo {solo_p99:.3f}s"
    )
    # Jain's fairness index over per-tenant weighted throughput: equal
    # weights, offered 10:1 — service tracks offered load (both tenants
    # in quota; fairness means neither is throttled below its share).
    served = {
        t: rec["served"] for t, rec in flood["by_tenant"].items()
    }
    offered = {"bully": 10.0, "victim": 1.0}
    xs = [served[t] / offered[t] for t in served]
    jain = sum(xs) ** 2 / (len(xs) * sum(x * x for x in xs))
    assert jain > 0.9, f"Jain index {jain:.3f} over {served}"


# -- scheduler DWRR fill ------------------------------------------------------


def test_scheduler_dwrr_fill_is_weight_proportional():
    """With both tenants backlogged in one class, batch slots fill by
    weight (2:1), deterministically — the noisy-neighbor mechanism."""
    from mpi4dl_tpu.serve.scheduler import ClassScheduler, normalize_classes

    class _Req:
        def __init__(self, deadline, tenant, tag):
            self.deadline = deadline
            self.slo_class = "default"
            self.tenant = tenant
            self.tag = tag

    s = ClassScheduler(
        normalize_classes(None), max_queue=64, mode="edf",
        tenants="heavy=none:2,light=none",
    )
    now = time.monotonic()
    # The bully floods with EARLIER deadlines than the victim — EDF
    # alone would serve all of heavy first; DWRR must interleave.
    for i in range(12):
        s.put(_Req(now + 1.0 + i * 1e-3, "heavy", f"h{i}"))
    for i in range(6):
        s.put(_Req(now + 10.0 + i * 1e-3, "light", f"l{i}"))
    reqs, _ = s.take(18, first_timeout_s=0.5)
    tags = [r.tag for r in reqs]
    assert len(tags) == 18
    # Every 3-slot window holds a light request: 2:1, no starvation.
    light_positions = [i for i, t in enumerate(tags) if t.startswith("l")]
    assert light_positions[0] <= 3
    gaps = np.diff([-1] + light_positions)
    assert max(gaps) <= 4, tags
    # Per-tenant depth introspection drains to zero.
    assert all(v == 0 for v in s.qsize_by_tenant()["default"].values())
