"""ISSUE 16 tentpole: the static communication cost model
(:mod:`mpi4dl_tpu.analysis.costmodel`) on canned collective records —
pricing formulas per op class, program-level prediction shape, the
no-claim rule for sync-only programs, gauge publication through the
metric catalog, the crosscheck severities, and the pure-JSON artifact
mode. No jax, no compile: the live end-to-end path is exercised by
``analyze costmodel`` (slow tier) and the bench extras."""

import json

import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.analysis.costmodel import (
    DEFAULT_TOLERANCE,
    INTERCONNECTS,
    artifact_main,
    collective_seconds,
    crosscheck_cost_model,
    predict_from_report,
    predict_program,
    publish_prediction,
)

ICI = INTERCONNECTS["ici"]
CPU = INTERCONNECTS["cpu"]
MB = 1024 * 1024


def _rec(opcode, bytes_moved, is_async=False, compute_between=0):
    return {"opcode": opcode, "bytes_moved": bytes_moved,
            "is_async": is_async, "compute_between": compute_between}


# -- pricing formulas ---------------------------------------------------------

def test_interconnect_table_priors():
    """The committed priors the ICI campaign falsifies: a TPU-v4-ish
    torus link vs the shared-heap CPU 'link'."""
    assert ICI.bandwidth_bytes_per_s == pytest.approx(100e9)
    assert ICI.latency_s == pytest.approx(1e-6)
    assert CPU.bandwidth_bytes_per_s == pytest.approx(10e9)
    assert CPU.latency_s == pytest.approx(5e-6)
    assert DEFAULT_TOLERANCE == 0.15


def test_permute_is_one_hop():
    t = collective_seconds("collective-permute", MB, ICI, 8)
    assert t == pytest.approx(ICI.latency_s + MB / ICI.bandwidth_bytes_per_s)


@pytest.mark.parametrize("op", ["all-gather", "reduce-scatter", "all-to-all"])
def test_ring_ops_scale_with_device_count(op):
    n = 8
    t = collective_seconds(op, MB, ICI, n)
    assert t == pytest.approx(
        (n - 1) * ICI.latency_s
        + (n - 1) / n * MB / ICI.bandwidth_bytes_per_s
    )
    # More devices → more latency terms, payload share → 1: monotone up.
    assert collective_seconds(op, MB, ICI, 16) > t


def test_all_reduce_doubles_the_ring():
    """Ring all-reduce = reduce-scatter + all-gather phases."""
    assert collective_seconds("all-reduce", MB, ICI, 8) == pytest.approx(
        2 * collective_seconds("all-gather", MB, ICI, 8)
    )


def test_unknown_op_prices_one_full_payload_hop():
    assert collective_seconds("quantum-entangle", MB, ICI, 8) == (
        pytest.approx(collective_seconds("collective-permute", MB, ICI, 8))
    )


# -- program-level prediction -------------------------------------------------

def test_sync_only_program_makes_no_overlap_claim():
    """Every CPU-mesh program: collectives exist but none are async —
    predicted achievable overlap 0.0 with the claim OFF, so the
    crosscheck stays silent whatever the runtime measured (sync
    collectives say nothing about what an async lowering could hide)."""
    pred = predict_program(
        [_rec("collective-permute", MB) for _ in range(4)],
        interconnect="cpu",
    )
    assert pred["n_collectives"] == 4 and pred["n_async"] == 0
    assert pred["overlap_claim"] is False
    assert pred["overlap_ratio"] == 0.0
    assert pred["comms_s"] == pytest.approx(
        4 * collective_seconds("collective-permute", MB, CPU, 8)
    )
    assert pred["exposed_s"] == pytest.approx(pred["comms_s"])
    assert crosscheck_cost_model(pred, measured_overlap=0.97) == []


def test_async_with_compute_between_is_hideable():
    """Achievable = async window AND compute already scheduled inside it
    — an async pair with an empty window hides nothing (the T3 rule)."""
    hidden = _rec("collective-permute", MB, is_async=True, compute_between=3)
    empty = _rec("collective-permute", MB, is_async=True, compute_between=0)
    sync = _rec("collective-permute", MB)
    pred = predict_program([hidden, empty, sync], interconnect="ici")
    one = collective_seconds("collective-permute", MB, ICI, 8)
    assert pred["overlap_claim"] is True and pred["n_async"] == 2
    assert pred["hideable_s"] == pytest.approx(one, abs=1e-9)
    assert pred["comms_s"] == pytest.approx(3 * one, abs=1e-9)
    assert pred["overlap_ratio"] == pytest.approx(1 / 3, abs=1e-4)
    assert pred["per_op"]["collective-permute"]["count"] == 3


def test_predict_from_report_reads_config():
    d = {
        "module_name": "m",
        "config": {"program": "sp2x2_train", "n_devices": 4},
        "collectives": [_rec("all-gather", MB)],
    }
    pred = predict_from_report(d, interconnect="ici")
    assert pred["program"] == "sp2x2_train"
    assert pred["n_devices"] == 4
    assert pred["comms_s"] == pytest.approx(
        collective_seconds("all-gather", MB, ICI, 4), abs=1e-9
    )
    # Bubble passthrough: the schedule model's number rides unmodified.
    pred = predict_from_report(d, analytic_bubble=0.2)
    assert pred["bubble_fraction"] == 0.2


# -- gauges through the catalog ----------------------------------------------

def test_publish_prediction_uses_cataloged_gauges():
    reg = telemetry.MetricsRegistry()
    pred = predict_program(
        [_rec("collective-permute", MB, is_async=True, compute_between=2)],
        interconnect="ici", analytic_bubble=0.2,
    )
    pred["program"] = "pipeline_gpipe"
    publish_prediction(pred, reg)
    labels = {"program": "pipeline_gpipe", "interconnect": "ici"}
    assert reg.get("hlolint_predicted_comms_seconds").value(**labels) == (
        pytest.approx(pred["comms_s"])
    )
    assert reg.get("hlolint_predicted_overlap_ratio").value(**labels) == 1.0
    assert reg.get("hlolint_predicted_bubble_fraction").value(**labels) == 0.2


# -- crosscheck severities ----------------------------------------------------

def _claiming_pred(ratio, bubble=None):
    return {"overlap_claim": True, "overlap_ratio": ratio,
            "bubble_fraction": bubble}


def test_crosscheck_measured_above_ceiling_is_an_error():
    (f,) = crosscheck_cost_model(_claiming_pred(0.5), measured_overlap=0.8)
    assert f.rule == "cost-model-crosscheck" and f.severity == "error"
    assert "ceiling" in f.message


def test_crosscheck_measured_below_ceiling_is_info():
    (f,) = crosscheck_cost_model(_claiming_pred(0.9), measured_overlap=0.5)
    assert f.severity == "info"


def test_crosscheck_within_tolerance_is_clean():
    assert crosscheck_cost_model(
        _claiming_pred(0.6), measured_overlap=0.6 + DEFAULT_TOLERANCE / 2
    ) == []


def test_crosscheck_bubble_disagreement_is_an_error():
    (f,) = crosscheck_cost_model(
        _claiming_pred(0.0, bubble=0.2), measured_bubble=0.45,
    )
    assert f.severity == "error" and "bubble" in f.message
    assert crosscheck_cost_model(
        _claiming_pred(0.0, bubble=0.2), measured_bubble=0.21,
    ) == []


# -- artifact mode (pure JSON, in-process) ------------------------------------

def test_artifact_main_prices_committed_reports(tmp_path, capsys):
    rep = tmp_path / "report.json"
    rep.write_text(json.dumps({
        "module_name": "m",
        "config": {"program": "sp2x2_train", "n_devices": 8},
        "collectives": [_rec("collective-permute", MB)] * 20,
    }))
    out = tmp_path / "pred.json"
    rc = artifact_main([str(rep), "--interconnect", "ici",
                        "--json", str(out)])
    assert rc == 0
    assert "costmodel[sp2x2_train] ici" in capsys.readouterr().out
    payload = json.loads(out.read_text())
    (pred,) = payload["predictions"]
    assert pred["source"] == str(rep)
    assert pred["n_collectives"] == 20
    assert pred["comms_s"] == pytest.approx(
        20 * collective_seconds("collective-permute", MB, ICI, 8)
    )


def test_committed_ici_artifact_reprices_consistently():
    """The committed campaign artifact (docs/artifacts/) must stay
    internally consistent: every program entry carries the ici
    interconnect, a sync-only no-claim (CPU-mesh compiles), and a
    positive priced comms time — so real-hardware numbers have a
    well-formed prediction to falsify."""
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "artifacts", "costmodel_ici_r01.json",
    )
    doc = json.load(open(path))
    assert doc["interconnect"] == "ici" and doc["round"] == "r01"
    assert set(doc["programs"]) >= {
        "sp2x2_train", "pipeline_gpipe", "pipeline_1f1b",
    }
    for name, entry in doc["programs"].items():
        pred = entry["prediction"]
        assert pred["interconnect"] == "ici", name
        assert pred["comms_s"] > 0, name
        assert pred["overlap_claim"] is False, name
        # The committed CPU-mesh crosscheck was clean — the campaign
        # starts from a model the live gauges did not contradict.
        assert entry["crosscheck"] == [], name
        assert entry["lint_errors"] == [], name
        assert entry["tolerance"] == DEFAULT_TOLERANCE, name
        if name.startswith("pipeline_"):
            assert pred["bubble_fraction"] > 0, name
            assert pred["bubble_fraction"] == pytest.approx(
                entry["measured"]["pipeline_bubble_fraction"]
            ), name
