"""AmoebaNet-D D2 (fused-halo) tests: one wide exchange per cell input state
plus VALID ops with per-op crops (``AmoebaCellD2``) must reproduce the plain
single-device model bit-for-bit — the property the reference's
``amoebanet_d2.py`` asserts only by construction.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from mpi4dl_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.models.amoebanet import (
    NORMAL_OPERATIONS,
    _plan_state_halos,
    amoebanetd,
)
from mpi4dl_tpu.parallel.partition import init_cells


def _forward(cells, params, x):
    h = x
    for c, p in zip(cells, params):
        h = c.apply(p, h)
    return h


def test_halo_plan_for_normal_genotype():
    """State 0 (s1) needs halo 3 (its 1x7-7x1 chains), state 1 (s2) needs
    halo 2 (max-pool chain through state 2); state 2 carries halo 1; concat
    states end at halo 0. The derived plan reproduces exactly the reference's
    hand-chosen exchange widths (s3_layer halo=3, s4_layer halo=2,
    ``amoebanet_d2.py:569-632``) — derived, not tabled."""
    halos = _plan_state_halos(NORMAL_OPERATIONS)
    assert halos[0] == 3 and halos[1] == 2
    assert halos[2] == 1
    assert halos[3:] == [0, 0, 0, 0]


@pytest.mark.parametrize("n_spatial", [4])
def test_amoebanet_d2_forward_matches_plain(n_spatial):
    """D2 spatial front (stem + 2 reduction cells D1 + 1 fused-halo normal
    cell) == plain model activations on 2x2 tiles. Covers wide exchange,
    VALID 1x7/7x1 chains, crops, boundary-ring refill, interior-masked BN,
    and the D2 max/avg pools."""
    d2_cells = amoebanetd(
        num_layers=3, num_filters=32, spatial_cells=n_spatial, halo_d2=True
    )
    plain_cells = amoebanetd(num_layers=3, num_filters=32)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((1, 128, 128, 3)), jnp.float32)
    params = init_cells(plain_cells, jax.random.PRNGKey(0), x)

    golden = _forward(plain_cells[:n_spatial], params[:n_spatial], x)

    dev = np.asarray(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(dev, ("tile_h", "tile_w"))
    spec = P(None, "tile_h", "tile_w", None)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), spec), out_specs=spec, check_vma=False
    )
    def dist(p, tile):
        return _forward(d2_cells[:n_spatial], p, tile)

    xs = jax.device_put(x, NamedSharding(mesh, spec))
    out = dist(params[:n_spatial], xs)
    # Tolerance: interior-masked BN statistics sum in a different order than
    # the plain model's full-tile reduction; the residue is pure float
    # accumulation noise (observed max ~8e-5), far below any structural
    # halo/boundary error (order 1).
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), rtol=1e-3, atol=3e-4
        ),
        out,
        golden,
    )


@pytest.mark.slow
def test_amoebanet_d2_gradients_match_plain():
    """Gradient parity through the D2 cell (crops, custom boundary fills and
    interior-masked BN all under AD)."""
    n_spatial = 4
    d2_cells = amoebanetd(
        num_layers=3, num_filters=16, spatial_cells=n_spatial, halo_d2=True
    )
    plain_cells = amoebanetd(num_layers=3, num_filters=16)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((1, 128, 128, 3)), jnp.float32)
    params = init_cells(plain_cells, jax.random.PRNGKey(1), x)
    front_params = params[:n_spatial]

    def loss_plain(p):
        out = _forward(plain_cells[:n_spatial], p, x)
        return sum(jnp.sum(l * l) for l in jax.tree.leaves(out))

    g_plain = jax.jit(jax.grad(loss_plain))(front_params)

    dev = np.asarray(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(dev, ("tile_h", "tile_w"))
    spec = P(None, "tile_h", "tile_w", None)

    @jax.jit
    @jax.grad
    def g_d2_fn(p):
        from jax import lax

        def local(p, tile):
            out = _forward(d2_cells[:n_spatial], p, tile)
            return lax.psum(
                sum(jnp.sum(l * l) for l in jax.tree.leaves(out)),
                ("tile_h", "tile_w"),
            )

        fn = shard_map(
            local, mesh=mesh, in_specs=(P(), spec), out_specs=P(), check_vma=False
        )
        return fn(p, jax.device_put(x, NamedSharding(mesh, spec)))

    g_d2 = g_d2_fn(front_params)

    # Tolerance scaled to the global gradient magnitude: the sum-of-squares
    # loss routes ~1e2-magnitude cotangents everywhere, so leaves whose true
    # gradient is a near-cancelled sum (BN biases: sum of zero-mean
    # cotangents) have float noise set by the cotangent scale, not their own
    # value — per-element rtol there flags pure noise. Structural halo bugs
    # diverge at the cotangent scale and are still caught.
    global_scale = max(
        float(np.max(np.abs(np.asarray(l)))) for l in jax.tree.leaves(g_plain)
    )

    def check(u, v):
        np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), rtol=2e-3, atol=2e-4 * global_scale
        )

    jax.tree.map(check, g_d2, g_plain)
