"""Numerics sentinel (:mod:`mpi4dl_tpu.telemetry.canary`) — golden-probe
derivation, digest semantics, parameter-integrity checksums, the
CanaryState verdict machine (ok / tolerance / divergence / error /
skipped), the fleet-side :func:`numerics_skew` scoring goldens, and the
engine integration: references recorded at warm-up into the footprint
ledger, a canary riding the REAL dispatch path with ``outcome="canary"``
off the client books, and ``corrupt_params`` → detection → fence
callback + schema-valid ``canary.failure`` events.

Determinism note: the integration tests never sleep on the sentinel
daemon — they call ``inject_canary()`` / ``record_checksum`` directly
and wait on the returned Future, so verdicts are synchronous facts.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.evaluate import collect_batch_stats
from mpi4dl_tpu.models.resnet import get_resnet_v2
from mpi4dl_tpu.parallel.partition import init_cells
from mpi4dl_tpu.serve import ServingEngine
from mpi4dl_tpu.telemetry.canary import (
    CANARY_ATOL,
    CanarySentinel,
    CanaryState,
    canary_example,
    corrupt_params,
    exact_digest,
    flip_bits,
    params_checksum,
    quantized_digest,
    ulp_diff,
)
from mpi4dl_tpu.telemetry.federation import numerics_skew
from mpi4dl_tpu.telemetry.slo import availability_objective
from mpi4dl_tpu.utils import get_depth

SIZE = 16


# -- probe derivation ---------------------------------------------------------


def test_canary_example_deterministic_and_fact_sensitive():
    a = canary_example((SIZE, SIZE, 3), "float32", seed=0)
    b = canary_example((SIZE, SIZE, 3), "float32", seed=0)
    assert a.dtype == np.float32 and a.shape == (SIZE, SIZE, 3)
    np.testing.assert_array_equal(a, b)
    # Model-level facts each re-derive the probe; nothing else does.
    assert not np.array_equal(a, canary_example((SIZE, SIZE, 3), seed=1))
    assert canary_example((8, 8, 3)).shape == (8, 8, 3)
    assert not np.array_equal(
        a[:8, :8], canary_example((8, 8, 3))[:8, :8]
    )


# -- digests ------------------------------------------------------------------


def test_digest_semantics_exact_vs_quantized():
    # Values parked a quarter-cell off the quantization grid, so a tiny
    # perturbation cannot straddle a cell boundary by coincidence.
    arr = ((np.arange(12, dtype=np.float64) + 0.25) * 2 * CANARY_ATOL).astype(
        np.float32
    )
    d, q = exact_digest(arr), quantized_digest(arr)
    assert d.startswith("xd") and len(d) == 18
    assert q.startswith("xq") and len(q) == 18
    assert exact_digest(arr.copy()) == d
    assert quantized_digest(arr.copy()) == q

    # Below-tolerance noise: exact digest (bitwise) moves, quantized
    # (the cross-executable comparison) does not.
    near = arr.copy()
    near[3] += 1e-9
    assert exact_digest(near) != d
    assert quantized_digest(near) == q

    # Beyond tolerance: both move.
    far = arr.copy()
    far[3] += 1e-3
    assert exact_digest(far) != d
    assert quantized_digest(far) != q

    # Shape is part of the digest material.
    assert exact_digest(arr.reshape(3, 4)) != d


def test_ulp_diff_counts_representable_floats():
    a = np.ones(5, np.float32)
    assert ulp_diff(a, a) == 0
    b = a.copy()
    b[2] = np.nextafter(np.float32(1.0), np.float32(2.0))
    assert ulp_diff(a, b) == 1
    # Monotone in the perturbation, and symmetric.
    c = a.copy()
    c[2] = np.float32(1.0 + 1e-3)
    assert ulp_diff(a, c) > ulp_diff(a, b)
    assert ulp_diff(c, a) == ulp_diff(a, c)
    # ±0.0 are the same point on the monotone integer line.
    assert ulp_diff(np.float32([-0.0]), np.float32([0.0])) == 0
    assert ulp_diff(np.float32([]), np.float32([])) == 0


# -- parameter integrity ------------------------------------------------------


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "conv": {"w": rng.standard_normal((4, 4)).astype(np.float32)},
        "dense": [rng.standard_normal(8).astype(np.float32)],
    }


def test_params_checksum_deterministic_and_bit_sensitive():
    params = _tree()
    stats = {"bn": np.ones(3, np.float32)}
    c = params_checksum(params, stats)
    assert c.startswith("pc") and len(c) == 18
    # Dict insertion order is not checksum material (sorted traversal).
    reordered = {"dense": params["dense"], "conv": params["conv"]}
    assert params_checksum(reordered, stats) == c
    # BN stats are covered too.
    assert params_checksum(params, None) != c
    # One flipped bit in one leaf moves it.
    mutated = {
        "conv": {"w": params["conv"]["w"].copy()},
        "dense": params["dense"],
    }
    flat = mutated["conv"]["w"].reshape(-1)
    flat.view(np.int32)[5] ^= np.int32(1)
    assert params_checksum(mutated, stats) != c


def test_flip_bits_targets_distinct_elements_and_is_involutive():
    arr = np.linspace(0.5, 2.0, 32).astype(np.float32)
    out, forensics = flip_bits(arr, bits=3, seed=7)
    assert forensics["bits"] == 3
    assert len(set(forensics["indices"])) == 3
    # Original untouched; exactly the named elements changed.
    assert np.array_equal(arr, np.linspace(0.5, 2.0, 32).astype(np.float32))
    changed = np.flatnonzero(out != arr)
    assert sorted(changed.tolist()) == sorted(forensics["indices"])
    assert forensics["before"] != forensics["after"]
    # XOR of bit 30 is an involution: a second flip restores bitwise.
    back, _ = flip_bits(out, bits=3, seed=7)
    np.testing.assert_array_equal(back, arr)
    # bits clamps to the buffer size (and to at least one element).
    _, f = flip_bits(np.ones(2, np.float32), bits=99, seed=0)
    assert f["bits"] == 2


class _FakePredictor:
    """param_tree/reload_params contract double for corrupt_params."""

    def __init__(self):
        rng = np.random.default_rng(3)
        self.params = {
            "big": rng.standard_normal(64).astype(np.float32),
            "small": rng.standard_normal(4).astype(np.float32),
            "ints": np.arange(4, dtype=np.int32),
        }
        self.stats = {"bn": np.ones(2, np.float32)}
        self.reloaded = None

    def param_tree(self):
        return self.params, self.stats

    def reload_params(self, params):
        self.reloaded = params


def test_corrupt_params_hits_largest_f32_leaf_via_reload():
    pred = _FakePredictor()
    forensics = corrupt_params(pred, bits=2, seed=1)
    assert forensics["leaf"] == "/big"
    assert forensics["leaf_size"] == 64
    assert forensics["bits"] == 2
    assert pred.reloaded is not None
    # Only the named leaf changed; the rest of the tree rode through.
    assert not np.array_equal(pred.reloaded["big"], pred.params["big"])
    np.testing.assert_array_equal(pred.reloaded["small"], pred.params["small"])
    np.testing.assert_array_equal(pred.reloaded["ints"], pred.params["ints"])
    # The live buffers were swapped, not mutated in place — and the
    # checksum baseline was deliberately NOT updated (the sentinel must
    # discover the corruption, not be told about it).
    assert params_checksum(pred.reloaded, pred.stats) != params_checksum(
        pred.params, pred.stats
    )


# -- CanaryState verdicts -----------------------------------------------------


class _Sink:
    enabled = True

    def __init__(self):
        self.events = []

    def write(self, ev):
        self.events.append(ev)

    record = write  # flight-ring protocol


def _state(**kw):
    kw.setdefault("events", _Sink())
    kw.setdefault("flight", _Sink())
    kw.setdefault("device", "cpu:0")
    kw.setdefault("program", "serve_predict")
    return CanaryState(**kw)


def _ref_row():
    return ((np.arange(10, dtype=np.float64) + 0.25) * 2 * CANARY_ATOL).astype(
        np.float32
    )


def test_canary_state_verdict_machine():
    st = _state(registry=telemetry.MetricsRegistry())
    fired = []
    st.on_failure(lambda attrs: 1 / 0)  # a dead fence callback...
    st.on_failure(fired.append)  # ...must not stop the next one

    rec = st.record_reference(4, _ref_row(), fingerprint="fp-a")
    assert rec["digest"].startswith("xd")
    assert rec["qdigest"].startswith("xq")

    # ok: bitwise match inside one executable fingerprint.
    v = st.verify(4, _ref_row(), fingerprint="fp-a")
    assert v["result"] == "ok" and st.failures == 0

    # tolerance: bitwise differs, within the documented f32 bound —
    # a recompiled executable, not corruption.
    near = _ref_row()
    near[0] += 1e-6
    v = st.verify(4, near, fingerprint="fp-b")
    assert v["result"] == "tolerance"
    assert v["ulp"] >= 1 and v["max_abs"] <= CANARY_ATOL
    assert st.failures == 0 and not fired

    # divergence: beyond tolerance — event + fence callbacks.
    far = _ref_row()
    far[1] += 1e-2
    v = st.verify(4, far, fingerprint="fp-a")
    assert v["result"] == "divergence"
    assert v["max_abs"] == pytest.approx(1e-2, rel=1e-3)
    assert st.failures == 1
    assert st.max_divergence == pytest.approx(1e-2, rel=1e-3)
    assert fired and fired[-1]["check"] == "probe"
    assert fired[-1]["expected_digest"] != fired[-1]["got_digest"]

    # error: no reference for the bucket — a verify bug, not a verdict.
    assert st.verify(8, _ref_row())["result"] == "error"

    # skipped: a canary round that could not run.
    st.skip("queue full")
    assert st.last == {
        "result": "skipped", "reason": "queue full", "ts": st.last["ts"],
    }

    # Every verdict burned a cataloged counter sample.
    checks = st._m_checks
    for result in ("ok", "tolerance", "divergence", "error", "skipped"):
        assert checks.value(result=result) == 1.0, result
    assert st._m_divergence.value() == pytest.approx(1e-2, rel=1e-3)

    # The failure event is schema-valid and landed in BOTH sinks.
    for sink in (st.events, st.flight):
        evs = [e for e in sink.events if e["name"] == "canary.failure"]
        assert len(evs) == 1
        telemetry.validate_event(evs[0])
        assert evs[0]["attrs"]["bucket"] == 4
        assert evs[0]["attrs"]["program"] == "serve_predict"

    view = st.view()
    assert view["checks"] == 4  # ok, tolerance, divergence, error
    assert view["failures"] == 1
    assert view["buckets"]["4"]["fingerprint"] == "fp-a"
    assert "row" not in view["buckets"]["4"]  # arrays stripped


def test_canary_state_checksum_drift_is_a_divergence():
    st = _state()
    fired = []
    st.on_failure(fired.append)
    assert st.record_checksum("pcaaaa", load=True)
    assert st.load_checksum == "pcaaaa"
    assert st.record_checksum("pcaaaa")  # steady state: never moves
    assert st.failures == 0
    assert not st.record_checksum("pcbbbb")  # torn restore / bit-flip
    assert st.failures == 1
    assert fired[-1]["check"] == "params_checksum"
    assert fired[-1]["expected"] == "pcaaaa"
    assert fired[-1]["got"] == "pcbbbb"
    assert st.view()["params_checksum"] == "pcbbbb"
    assert st.view()["load_checksum"] == "pcaaaa"
    # First record without load= also becomes the baseline.
    st2 = _state()
    assert st2.record_checksum("pccccc")
    assert st2.load_checksum == "pccccc"


def test_canary_sentinel_cadence_and_crash_isolation():
    ticks = []

    def tick():
        ticks.append(time.time())
        if len(ticks) == 1:
            raise RuntimeError("one bad tick must not kill the daemon")

    s = CanarySentinel(tick, interval_s=0.01, name="t")
    s.start()
    deadline = time.time() + 5.0
    while len(ticks) < 3 and time.time() < deadline:
        time.sleep(0.01)
    s.stop()
    assert len(ticks) >= 3
    assert s.ticks >= 2  # .ticks counts completed ticks; #1 raised
    n = len(ticks)
    time.sleep(0.05)
    assert len(ticks) == n  # stopped means stopped


# -- federation scoring goldens ----------------------------------------------


def _replica(checksum="pcaaaa", failures=0, fenced=False, load=None,
             qdigest="xq1", digest="xd1", fp="fp-a"):
    return {
        "failures": failures,
        "fenced": fenced,
        "params_checksum": checksum,
        "load_checksum": load if load is not None else checksum,
        "buckets": {"4": {"digest": digest, "qdigest": qdigest,
                          "fingerprint": fp}},
    }


def test_numerics_skew_healthy_fleet_scores_zero():
    out = numerics_skew({"r0": _replica(), "r1": _replica()})
    assert out["score"] == {"r0": 0.0, "r1": 0.0}
    assert out["evidence"] == {"r0": [], "r1": []}


def test_numerics_skew_self_report_is_paging_evidence():
    out = numerics_skew({
        "r0": _replica(),
        "r1": _replica(failures=2, fenced=True, load="pcload"),
    })
    # failures + fence + checksum drift: 1.0 each, all on the reporter.
    assert out["score"]["r1"] == pytest.approx(3.0)
    assert out["score"]["r0"] == 0.0
    assert len(out["evidence"]["r1"]) == 3


def test_numerics_skew_checksum_majority_outvotes_silent_corruption():
    out = numerics_skew({
        "r0": _replica("pcaaaa"),
        "r1": _replica("pcaaaa"),
        "r2": _replica("pcbbbb", load="pcbbbb"),  # corrupt since load
    })
    assert out["score"]["r2"] == pytest.approx(1.0)
    assert out["score"]["r0"] == out["score"]["r1"] == 0.0
    assert any("majority" in e for e in out["evidence"]["r2"])


def test_numerics_skew_two_replica_split_is_evidence_not_score():
    out = numerics_skew({
        "r0": _replica("pcaaaa"),
        "r1": _replica("pcbbbb", load="pcbbbb"),
    })
    # 1v1: neither can out-vote the other — surfaced, unscored.
    assert out["score"] == {"r0": 0.0, "r1": 0.0}
    assert any("no majority" in e for e in out["evidence"]["r0"])
    assert any("no majority" in e for e in out["evidence"]["r1"])


def test_numerics_skew_exact_digest_vote_within_fingerprint():
    # Same model and params checksums, same executable fingerprint —
    # but one replica warmed up with a different bitwise reference.
    out = numerics_skew({
        "r0": _replica(digest="xd1"),
        "r1": _replica(digest="xd1"),
        "r2": _replica(digest="xd9"),
    })
    assert out["score"]["r2"] == pytest.approx(1.0)
    assert out["score"]["r0"] == 0.0


def test_numerics_skew_qdigest_minority_is_advisory():
    # Different fingerprints (no exact-vote group) — the quantized
    # digest is the only comparison and must stay below the 1.0 page
    # threshold by itself (grid straddles exist by construction).
    out = numerics_skew({
        "r0": _replica(fp="fp-a", qdigest="xq1"),
        "r1": _replica(fp="fp-b", qdigest="xq1"),
        "r2": _replica(fp="fp-c", qdigest="xq9"),
    })
    assert out["score"]["r2"] == pytest.approx(0.4)
    assert out["score"]["r2"] < 1.0
    assert out["score"]["r0"] == 0.0


def test_canary_outcome_excluded_from_availability():
    obj = availability_objective(0.999)
    assert "canary" in obj.ignore_outcomes
    assert "drained" in obj.ignore_outcomes


# -- engine integration -------------------------------------------------------


@pytest.fixture(scope="module")
def model():
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=SIZE // 4
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    cal = [jnp.asarray(rng.standard_normal((4, SIZE, SIZE, 3)), jnp.float32)]
    stats = collect_batch_stats(cells, params, cal)
    return cells, params, stats


def _engine(model, **kw):
    cells, params, stats = model
    kw.setdefault("example_shape", (SIZE, SIZE, 3))
    kw.setdefault("max_batch", 4)
    kw.setdefault("default_deadline_s", 30.0)
    return ServingEngine(cells, params, stats, **kw)


def test_engine_warmup_records_references_and_baseline(model):
    eng = _engine(model)
    view = eng.canary.view()
    # One golden reference per warm bucket, annotated into the SAME
    # footprint-ledger entry as the executable fingerprint.
    assert sorted(int(b) for b in view["buckets"]) == [1, 2, 4]
    for b, ref in view["buckets"].items():
        assert ref["digest"].startswith("xd")
        assert ref["qdigest"].startswith("xq")
        entry = eng.memory_ledger.get(
            eng._predictor.program, bucket=int(b)
        )
        assert entry["canary_digest"] == ref["digest"]
        assert entry["canary_qdigest"] == ref["qdigest"]
        assert ref["fingerprint"] == entry.get("fingerprint")
    # Load-time integrity baseline is live and self-consistent.
    assert view["load_checksum"] == view["params_checksum"]
    assert view["params_checksum"] == eng.params_checksum()
    assert view["params_checksum"].startswith("pc")
    # The probe derives from model facts only: a second engine over the
    # same model computes the identical canary input and checksum.
    np.testing.assert_array_equal(
        eng._canary_x, canary_example((SIZE, SIZE, 3), "float32", seed=0)
    )


def test_engine_canary_rides_real_dispatch_off_client_books(model):
    eng = _engine(model)
    eng.start()
    try:
        fut = eng.inject_canary()
        assert fut is not None
        row = np.asarray(fut.result(timeout=60))
        assert row.shape == (10,)
        view = eng.canary.view()
        assert view["last"]["result"] == "ok"  # bitwise, same executable
        assert view["failures"] == 0
        # Off the client books: outcome "canary", nothing served.
        s = eng.stats()
        assert s["canary"] == 1
        assert s["served"] == 0
        assert s["submitted"] == 0
        # Client traffic alongside canaries keeps its own ledger.
        xs = [np.zeros((SIZE, SIZE, 3), np.float32) for _ in range(3)]
        for f in [eng.submit(x) for x in xs]:
            f.result(timeout=60)
        s = eng.stats()
        assert s["served"] == 3 and s["canary"] == 1
        req = telemetry.declare(eng.registry, "serve_requests_total")
        assert req.value(outcome="canary") == 1.0
        checks = telemetry.declare(eng.registry, "canary_checks_total")
        assert checks.value(result="ok") == 1.0
        # A full sentinel tick = checksum audit + probe; steady state
        # concludes ok on both with no failure.
        eng._canary_tick()
        deadline = time.time() + 30
        while eng.canary.checks < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert eng.canary.checks >= 2
        assert eng.canary.failures == 0
    finally:
        eng.stop()


def test_engine_corruption_detected_fenced_and_logged(model, tmp_path):
    eng = _engine(model, telemetry_dir=str(tmp_path))
    fired = []
    fence = threading.Event()

    def on_failure(attrs):
        fired.append(attrs)
        fence.set()

    eng.canary.on_failure(on_failure)
    eng.start()
    try:
        # Healthy probe first: the baseline verdict this drill flips.
        eng.inject_canary().result(timeout=60)
        assert eng.canary.view()["last"]["result"] == "ok"

        forensics = eng.corrupt_params(bits=3, seed=1)
        assert forensics["bits"] == 3 and forensics["leaf"]
        # Corruption is silent by design: nothing fires until the
        # sentinel looks.
        assert not fired

        # Checksum audit discovers the drift...
        assert not eng.canary.record_checksum(eng.params_checksum())
        assert fence.is_set()
        assert fired[0]["check"] == "params_checksum"

        # ...and the probe independently proves wrong ANSWERS, with
        # max-abs/ulp forensics (an exponent bit-flip in the largest
        # conv leaf lands far beyond the documented f32 bound).
        fut = eng.inject_canary()
        assert fut is not None
        fut.result(timeout=60)
        view = eng.canary.view()
        assert view["last"]["result"] == "divergence"
        assert view["last"]["check"] == "probe"
        assert view["last"]["max_abs"] > CANARY_ATOL
        assert view["last"]["ulp"] > 0
        assert view["failures"] >= 2
        assert view["max_divergence"] > CANARY_ATOL
        assert eng.stats()["numerics"]["failures"] >= 2
    finally:
        eng.stop()

    # The paper trail survives in the JSONL log: schema-valid
    # canary.failure events for BOTH detection paths.
    evs = []
    for log in tmp_path.glob("*.jsonl"):
        evs += [
            e for e in telemetry.read_events(str(log))
            if e.get("name") == "canary.failure"
        ]
    checks = sorted({e["attrs"]["check"] for e in evs})
    assert checks == ["params_checksum", "probe"]
    for e in evs:
        assert e["attrs"]["program"] == "serve_predict"
        assert e["attrs"]["load_checksum"] != e["attrs"]["current_checksum"]
