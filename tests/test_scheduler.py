"""Continuous batching + SLO-class EDF scheduling (ISSUE 11 tentpole):
``mpi4dl_tpu/serve/scheduler.py`` core goldens (class spec parsing, EDF
ordering across classes, fifo baseline order, starvation bound, burn-rate
feedback deprioritize/shed), the engine integration (admission-time
deadline rejection, per-class queue isolation + retry hints, multi-image
split/re-join bit-identity, per-class metrics + burn gauges, tail.sample
class tagging), the fleet propagation seam (worker RPC + router
shedding), and the live A/B: under a mixed tight/bulk load, the tight
class's p99 beats the FIFO windowed former (tier-1, CPU).
"""

import threading
import time
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.evaluate import collect_batch_stats
from mpi4dl_tpu.models.resnet import get_resnet_v2
from mpi4dl_tpu.parallel.partition import init_cells
from mpi4dl_tpu.serve import (
    DeadlineExceededError,
    QueueFullError,
    ServingEngine,
    SLOClass,
    parse_slo_classes,
)
from mpi4dl_tpu.serve.scheduler import (
    ClassFeedback,
    ClassScheduler,
    SchedulerFull,
    normalize_classes,
)
from mpi4dl_tpu.utils import get_depth

SIZE = 16


@pytest.fixture(scope="module")
def model():
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=SIZE // 4
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    cal = [jnp.asarray(rng.standard_normal((4, SIZE, SIZE, 3)), jnp.float32)]
    stats = collect_batch_stats(cells, params, cal)
    return cells, params, stats


def _engine(model, **kw):
    cells, params, stats = model
    kw.setdefault("example_shape", (SIZE, SIZE, 3))
    kw.setdefault("max_batch", 4)
    kw.setdefault("default_deadline_s", 30.0)
    return ServingEngine(cells, params, stats, **kw)


def _examples(n, seed=1):
    rng = np.random.default_rng(seed)
    return [
        rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)
        for _ in range(n)
    ]


class _Req:
    """Scheduler duck-type: deadline + slo_class, form_t stamped."""

    def __init__(self, deadline, slo_class="default", tag=None):
        self.deadline = deadline
        self.slo_class = slo_class
        self.tag = tag
        self.form_t = 0.0


# -- class spec + normalization ----------------------------------------------


def test_parse_slo_classes_goldens():
    tight, bulk = parse_slo_classes("tight=50ms:99.9@200ms,bulk=2s")
    assert tight.name == "tight"
    assert tight.latency_threshold_s == pytest.approx(0.05)
    assert tight.target == pytest.approx(0.999)
    assert tight.deadline_s == pytest.approx(0.2)
    assert bulk.latency_threshold_s == pytest.approx(2.0)
    assert bulk.target == pytest.approx(0.99)
    assert bulk.deadline_s is None
    # A class with no objective (pure scheduling bucket).
    (free,) = parse_slo_classes("free=none@5s")
    assert free.latency_threshold_s is None
    assert free.objective() is None
    # The objective: metric + labels select the class's series, the slo
    # label value is what the scheduler's feedback reads back.
    obj = tight.objective()
    assert obj.metric == "serve_class_latency_seconds"
    assert obj.labels == (("slo_class", "tight"), ("tenant", "default"))
    assert obj.name == "latency_tight"
    # Tenant-scoped objective: same class, a per-tenant series + burn.
    obj_b = tight.objective(tenant="bulk")
    assert obj_b.labels == (("slo_class", "tight"), ("tenant", "bulk"))
    assert obj_b.tenant == "bulk"
    with pytest.raises(ValueError, match="NAME=THRESHOLD"):
        parse_slo_classes("tight")
    with pytest.raises(ValueError, match="duplicate"):
        parse_slo_classes("a=1s,a=2s")
    with pytest.raises(ValueError, match="must match"):
        parse_slo_classes("Bad-Name=1s")
    # normalize: None -> the implicit default class.
    (default,) = normalize_classes(None)
    assert default.name == "default" and default.objective() is None


def test_class_mix_rotation_is_deterministic():
    from mpi4dl_tpu.serve.loadgen import ClassMix

    mix = ClassMix({"tight": (1, 10.0), "bulk": (3, None)})
    pattern = [mix.next()[0] for _ in range(8)]
    mix2 = ClassMix.parse("tight:1:10s,bulk:3")
    assert pattern == [mix2.next()[0] for _ in range(8)]
    assert pattern.count("tight") == 2 and pattern.count("bulk") == 6
    # Smooth: tight is spread out, not bursty.
    assert pattern[0] == "bulk" or pattern[1] == "bulk"


# -- scheduler core goldens ---------------------------------------------------


def _sched(mode="edf", classes="tight=50ms,bulk=2s", cap=64, **kw):
    return ClassScheduler(
        normalize_classes(classes), max_queue=cap, mode=mode, **kw
    )


def test_edf_ordering_across_classes():
    s = _sched()
    now = time.monotonic()
    # Bulk arrives FIRST but with later deadlines; EDF pops tights first,
    # each class internally deadline-ordered.
    for i, d in enumerate((100.0, 90.0, 110.0)):
        s.put(_Req(now + d, "bulk", tag=f"b{i}"))
    for i, d in enumerate((10.0, 5.0)):
        s.put(_Req(now + d, "tight", tag=f"t{i}"))
    reqs, expired = s.take(10, first_timeout_s=0.1)
    assert not expired
    assert [r.tag for r in reqs] == ["t1", "t0", "b1", "b0", "b2"]


def test_fifo_mode_preserves_arrival_order():
    s = _sched(mode="fifo")
    now = time.monotonic()
    s.put(_Req(now + 100.0, "bulk", tag="b0"))
    s.put(_Req(now + 1.0, "tight", tag="t0"))
    s.put(_Req(now + 50.0, "bulk", tag="b1"))
    reqs, _ = s.take(10, first_timeout_s=0.1)
    assert [r.tag for r in reqs] == ["b0", "t0", "b1"]


def test_expired_requests_surface_separately():
    s = _sched()
    now = time.monotonic()
    s.put(_Req(now - 1.0, "tight", tag="dead"))
    s.put(_Req(now + 60.0, "tight", tag="live"))
    reqs, expired = s.take(10, first_timeout_s=0.1)
    assert [r.tag for r in reqs] == ["live"]
    assert [r.tag for r in expired] == ["dead"]
    assert expired[0].form_t > 0  # span boundary stamped for the reject


def test_starvation_bound_bulk_deadline_advances_to_front():
    """EDF's starvation bound IS the deadline: a queued bulk request
    outranks every tight arrival whose deadline lands after it, so bulk
    is served no later than its own deadline order — continuous tight
    traffic cannot push it back indefinitely."""
    s = _sched()
    now = time.monotonic()
    s.put(_Req(now + 5.0, "bulk", tag="bulk"))
    # Tight stream: early arrivals beat bulk, later ones (deadline past
    # bulk's) do not.
    s.put(_Req(now + 1.0, "tight", tag="t-early"))
    s.put(_Req(now + 9.0, "tight", tag="t-late"))
    reqs, _ = s.take(10, first_timeout_s=0.1)
    assert [r.tag for r in reqs] == ["t-early", "bulk", "t-late"]


def test_per_class_bounds_and_atomic_group_admission():
    s = _sched(cap=3)
    now = time.monotonic()
    for _ in range(3):
        s.put(_Req(now + 60.0, "bulk"))
    with pytest.raises(SchedulerFull) as ei:
        s.put(_Req(now + 60.0, "bulk"))
    assert ei.value.slo_class == "bulk" and not ei.value.shed
    # Class isolation: bulk full, tight still admits.
    s.put(_Req(now + 1.0, "tight"))
    assert s.qsize_by_class() == {"tight": 1, "bulk": 3}
    # Atomic group: a 3-row group over tight's remaining room (2 slots
    # free) admits nothing at all.
    group = [_Req(now + 2.0, "tight") for _ in range(3)]
    with pytest.raises(SchedulerFull):
        s.put_many(group)
    assert s.qsize_by_class()["tight"] == 1


def test_feedback_deprioritizes_and_sheds_slowest_burning_class():
    reg = telemetry.MetricsRegistry()
    burn = telemetry.declare(reg, "slo_burn_rate")
    classes = normalize_classes("tight=50ms,bulk=2s")
    fb = ClassFeedback(reg, classes, min_interval_s=0.0)
    # No burn data: nobody is deprioritized (evidence-only policy).
    assert fb.states() == {"tight": "normal", "bulk": "normal"}
    # Tight burns hot, bulk burns cold -> bulk (the slowest burner)
    # yields; the protected class never does.
    burn.set(20.0, slo="latency_tight", window="fast_long",
             tenant="default")
    burn.set(0.1, slo="latency_bulk", window="fast_long",
             tenant="default")
    assert fb.states() == {"tight": "normal", "bulk": "deprioritized"}
    # Both burning hot: nobody yields (can't rob Peter to pay Paul).
    burn.set(20.0, slo="latency_bulk", window="fast_long",
             tenant="default")
    assert fb.states() == {"tight": "normal", "bulk": "normal"}

    # Scheduler honors the state: a deprioritized class goes LAST even
    # with the earliest deadline, and sheds early at shed_ratio.
    burn.set(0.1, slo="latency_bulk", window="fast_long",
             tenant="default")
    s = ClassScheduler(
        classes, max_queue=8, registry=reg, mode="edf",
        feedback=fb, shed_ratio=0.5,
    )
    now = time.monotonic()
    s.put(_Req(now + 1.0, "bulk", tag="b"))     # earliest deadline...
    s.put(_Req(now + 50.0, "tight", tag="t"))
    reqs, _ = s.take(10, first_timeout_s=0.1)
    assert [r.tag for r in reqs] == ["t", "b"]  # ...still yields
    # Shed: bulk's effective bound is shed_ratio * capacity = 4.
    for _ in range(4):
        s.put(_Req(now + 60.0, "bulk"))
    with pytest.raises(SchedulerFull) as ei:
        s.put(_Req(now + 60.0, "bulk"))
    assert ei.value.shed and ei.value.slo_class == "bulk"
    assert s.shed_counts["bulk"] == 1
    assert reg.get("serve_class_shed_total").value(slo_class="bulk") == 1
    # Tight (protected) admits to the full bound.
    for _ in range(8):
        s.put(_Req(now + 1.0, "tight"))
    with pytest.raises(SchedulerFull) as ei:
        s.put(_Req(now + 1.0, "tight"))
    assert not ei.value.shed


# -- engine integration -------------------------------------------------------


def test_admission_time_deadline_rejection(model):
    """ISSUE satellite: an already-expired deadline is rejected at
    submit — the typed error on the future, the rejected_deadline
    outcome counted, and NO queue slot ever occupied."""
    eng = _engine(model)
    fut = eng.submit(_examples(1)[0], deadline_s=-0.5)
    with pytest.raises(DeadlineExceededError, match="admission"):
        fut.result(timeout=1)
    s = eng.stats()
    assert s["rejected_deadline"] == 1
    assert s["queue_depth"] == 0
    assert eng.registry.get("serve_requests_total").value(
        outcome="rejected_deadline"
    ) == 1
    eng.stop()


def test_queue_full_carries_class_and_scaled_hint(model):
    """ISSUE satellite: the queue-full error names the class whose queue
    rejected, and the retry hint scales with THAT class's backlog."""
    eng = _engine(
        model, max_queue=2, slo_classes="tight=50ms@30s,bulk=2s@60s",
    )
    eng.submit(_examples(1)[0], slo_class="bulk")
    eng.submit(_examples(1)[0], slo_class="bulk")
    with pytest.raises(QueueFullError) as ei:
        eng.submit(_examples(1)[0], slo_class="bulk")
    assert ei.value.slo_class == "bulk" and not ei.value.shed
    assert ei.value.retry_after_s is not None
    # Per-class isolation: tight still admits, and ITS hint is smaller
    # (empty backlog) than bulk's would be (full backlog).
    eng.submit(_examples(1)[0], slo_class="tight")
    assert eng.retry_after_hint("tight") <= eng.retry_after_hint("bulk")
    # Unknown class is a loud config error, not a silent misfile.
    with pytest.raises(ValueError, match="unknown SLO class"):
        eng.submit(_examples(1)[0], slo_class="nope")
    # stats() reflects the per-class queues.
    s = eng.stats()
    assert s["queue_depth_by_class"] == {"tight": 1, "bulk": 2}
    assert s["queue_depth"] == 3
    eng.start()
    eng.stop()


def test_multi_image_split_rejoin_bit_identity(model):
    """ISSUE tentpole: a 6-image submission against max_batch=4 splits
    into a bucket-4 and a bucket-2 dispatch and re-joins in order —
    each row BYTE-identical to the corresponding unsplit per-bucket
    forward (padding inertness + per-sample independence make the
    split provably invisible)."""
    eng = _engine(model, max_batch=4)
    x = np.stack(_examples(6))
    fut = eng.submit(x)  # queued before start: deterministic 4+2 split
    eng.start()
    try:
        got = fut.result(timeout=60)
    finally:
        eng.stop()
    assert got.shape == (6, 10)
    cells, params, stats = model
    want_4 = np.asarray(eng._predictor.run(eng._compiled[4], x[:4]))
    want_2 = np.asarray(eng._predictor.run(eng._compiled[2], x[4:6]))
    np.testing.assert_array_equal(got[:4], want_4)
    np.testing.assert_array_equal(got[4:6], want_2)
    # The outer future carries the shared trace identity; every row
    # counted as a served request.
    assert fut.trace_id
    assert fut.e2e_latency_s > 0
    s = eng.stats()
    assert s["served"] == 6 and s["submitted"] == 6
    assert s["bucket_dispatches"][4] == 1
    assert s["bucket_dispatches"][2] == 1


def test_multi_image_admission_is_atomic(model):
    eng = _engine(model, max_queue=4)
    with pytest.raises(QueueFullError):
        eng.submit(np.stack(_examples(6)))
    s = eng.stats()
    assert s["queue_depth"] == 0  # nothing half-admitted
    assert s["rejected_queue_full"] == 6
    eng.stop()


def test_per_class_metrics_burn_gauges_and_tail_class(model, tmp_path):
    """Mixed-class traffic populates serve_class_latency_seconds per
    class, the evaluator publishes per-class burn gauges (the
    scheduler's feedback signal), spans + tail.samples carry the
    class, and the class objectives appear on the SLO surface."""
    eng = _engine(
        model,
        slo_classes="tight=1ms:99@30s,bulk=2s:99@60s",
        telemetry_dir=str(tmp_path),
        tail_factor=0.0,          # trip line = the 1ms class threshold
        tail_min_interval_s=0.0,  # no rate limit: every trip captures
    )
    eng.start()
    try:
        examples = _examples(8)
        futs = [
            eng.submit(x, slo_class=("tight" if i % 2 else "bulk"))
            for i, x in enumerate(examples[:4])
        ]
        for f in futs:
            f.result(timeout=60)
        eng.slo.evaluate_once()
        # Traffic BETWEEN snapshots: a windowed burn needs a nonzero
        # histogram delta inside the window, not just pre-window totals.
        futs = [
            eng.submit(x, slo_class=("tight" if i % 2 else "bulk"))
            for i, x in enumerate(examples[4:])
        ]
        for f in futs:
            f.result(timeout=60)
        time.sleep(0.05)
        eng.slo.evaluate_once()
    finally:
        eng.stop()
    hist = eng.registry.get("serve_class_latency_seconds")
    by_class = {
        s["labels"]["slo_class"]: s["count"] for s in hist.snapshot_series()
    }
    assert by_class == {"tight": 4, "bulk": 4}
    # The burn gauges the feedback reads back (both classes, page
    # window) exist after the evaluator ticked.
    burn = eng.registry.get("slo_burn_rate")
    slos = {
        s["labels"]["slo"] for s in burn.snapshot_series()
        if s["labels"]["window"] == "fast_long"
    }
    assert {"latency_tight", "latency_bulk"} <= slos
    # Every request slower than the absurd 1ms threshold tail-sampled
    # with its class named (ISSUE satellite).
    samples = eng.tail.tail(50)
    assert samples, "no tail.sample captured despite the 1ms trip line"
    assert all("slo_class" in ev["attrs"] for ev in samples)
    assert {ev["attrs"]["slo_class"] for ev in samples} <= {"tight", "bulk"}
    # Span events carry the class end to end.
    (log,) = tmp_path.iterdir()
    served = [
        e for e in telemetry.read_events(str(log))
        if e["kind"] == "span" and e["name"] == "serve.request"
    ]
    assert len(served) == 8
    assert {e["attrs"]["slo_class"] for e in served} == {"tight", "bulk"}


def test_analyze_tail_names_the_class(model, tmp_path):
    """ISSUE satellite: `analyze tail` rows carry slo_class from the
    span segments, so a straggler page names the class."""
    from mpi4dl_tpu.analysis.tail import trace_report, worst_traces

    eng = _engine(
        model, slo_classes="tight=1ms:99@30s,bulk=2s:99@60s",
        telemetry_dir=str(tmp_path),
    )
    eng.start()
    try:
        fut = eng.submit(_examples(1)[0], slo_class="tight")
        fut.result(timeout=60)
    finally:
        eng.stop()
    (log,) = tmp_path.iterdir()
    events = telemetry.read_events(str(log))
    rows = worst_traces(events, 5)
    assert rows and rows[0]["slo_class"] == "tight"
    rep = trace_report(events, fut.trace_id)
    assert any(
        seg["attrs"].get("slo_class") == "tight" for seg in rep["segments"]
    )


# -- the A/B: tight-class p99 beats the FIFO former ---------------------------


def _run_arm(model, scheduler):
    """One arm of the structural A/B: 48 bulk requests pre-queued, then
    8 tight requests behind them. Under FIFO the tights drain the full
    bulk backlog first; under EDF they jump it. Completion ORDER (not
    wall time) is the structural signal; latency follows from it."""
    eng = _engine(
        model, max_batch=4, max_queue=256,
        slo_classes="tight=50ms:99@30s,bulk=2s:99@120s",
        scheduler=scheduler,
    )
    done = []
    lock = threading.Lock()

    def watch(name, fut):
        fut.add_done_callback(
            lambda f: (lock.acquire(), done.append(name), lock.release())
        )

    t0 = time.monotonic()
    lat = {"tight": [], "bulk": []}
    futs = []
    for x in _examples(48, seed=3):
        f = eng.submit(x, slo_class="bulk")
        watch("bulk", f)
        futs.append(("bulk", t0, f))
    for x in _examples(8, seed=4):
        f = eng.submit(x, slo_class="tight")
        watch("tight", f)
        futs.append(("tight", t0, f))
    eng.start()
    try:
        for name, t, f in futs:
            f.result(timeout=120)
            # e2e as the engine measured it (submit -> completion).
            lat[name].append(f.e2e_latency_s)
    finally:
        eng.stop()
    assert len(done) == 56
    # Position of the last tight completion in the completion order.
    last_tight = max(i for i, n in enumerate(done) if n == "tight")
    return last_tight, lat


def test_edf_tight_class_beats_fifo_former(model):
    """ISSUE acceptance (tier-1, CPU): under the mixed load, EDF serves
    every tight request before the bulk backlog (structural — the
    completion order is deterministic given the queue content), so the
    tight class's p99 beats the FIFO former's by construction."""
    from mpi4dl_tpu.profiling import percentiles

    last_tight_edf, lat_edf = _run_arm(model, "edf")
    last_tight_fifo, lat_fifo = _run_arm(model, "fifo")
    # EDF: all 8 tights complete within the first ~3 batches (the first
    # two takes pop tight's earlier deadlines first). FIFO: the tights
    # arrived last and complete last.
    assert last_tight_edf < 16, (
        f"EDF served the last tight request at completion position "
        f"{last_tight_edf}; expected it near the front"
    )
    assert last_tight_fifo >= 48, (
        f"FIFO served the last tight request at position "
        f"{last_tight_fifo}; expected it behind the 48-deep bulk backlog"
    )
    p99_edf = percentiles(lat_edf["tight"], (99,))["p99"]
    p99_fifo = percentiles(lat_fifo["tight"], (99,))["p99"]
    assert p99_edf < p99_fifo, (
        f"tight-class p99 {p99_edf * 1e3:.1f}ms (edf) !< "
        f"{p99_fifo * 1e3:.1f}ms (fifo)"
    )
    # Aggregate service is preserved: both arms served everything.
    assert len(lat_edf["bulk"]) == len(lat_fifo["bulk"]) == 48


# -- fleet propagation --------------------------------------------------------


def test_worker_predict_server_propagates_class():
    """The slo_class a router sends rides the worker's /predict into
    engine.submit (stub engine — no jax model needed)."""
    from mpi4dl_tpu.fleet.replica import ReplicaClient
    from mpi4dl_tpu.fleet.worker import _ChaosState, _predict_server

    seen = {}

    class StubEngine:
        def submit(self, x, deadline_s=None, trace_id=None, slo_class=None):
            seen["slo_class"] = slo_class
            seen["shape"] = tuple(x.shape)
            fut = Future()
            fut.set_result(np.zeros((10,), np.float32))
            fut.trace_id = trace_id
            fut.e2e_latency_s = 0.001
            return fut

    httpd = _predict_server(
        StubEngine(), _ChaosState(), threading.Event(), 0
    )
    try:
        client = ReplicaClient(
            "r0", f"http://127.0.0.1:{httpd.server_address[1]}"
        )
        logits, payload = client.predict(
            np.zeros((4, 4, 3), np.float32), "tid-1",
            deadline_s=5.0, timeout_s=5.0, slo_class="tight",
        )
    finally:
        httpd.shutdown()
    assert seen["slo_class"] == "tight"
    assert payload["trace_id"] == "tid-1"
    assert logits.shape == (10,)


def test_router_sheds_deprioritized_class_under_pressure():
    """ISSUE tentpole: the router applies the engine scheduler's OWN
    shedding policy at its admission edge — same ClassFeedback, same
    burn gauges, one policy."""
    from mpi4dl_tpu.fleet.router import Router
    from mpi4dl_tpu.serve.engine import DrainedError

    reg = telemetry.MetricsRegistry()
    burn = telemetry.declare(reg, "slo_burn_rate")
    burn.set(20.0, slo="latency_tight", window="fast_long",
             tenant="default")
    burn.set(0.1, slo="latency_bulk", window="fast_long",
             tenant="default")
    router = Router(
        example_shape=(4, 4, 3), registry=reg, max_queue=4,
        slo_classes="tight=50ms@30s,bulk=2s@60s", shed_queue_ratio=0.5,
    )
    x = np.zeros((4, 4, 3), np.float32)
    futs = [router.submit(x, slo_class="tight") for _ in range(2)]
    # Queue at the shed threshold (2/4): bulk (deprioritized) sheds...
    with pytest.raises(QueueFullError) as ei:
        router.submit(x, slo_class="bulk")
    assert ei.value.shed and ei.value.slo_class == "bulk"
    assert reg.get("serve_class_shed_total").value(slo_class="bulk") == 1
    # ...while tight still admits to the full bound.
    futs.append(router.submit(x, slo_class="tight"))
    assert router.stats()["shed"] == 1
    with pytest.raises(ValueError, match="unknown SLO class"):
        router.submit(x, slo_class="nope")
    router.stop(drain=False)
    for f in futs:
        with pytest.raises(DrainedError):
            f.result(timeout=5)
