"""Real-TPU compile smoke tests.

Round 1 shipped a Pallas kernel that passed every interpreter-mode test but
failed Mosaic compilation on the device, crashing the headline bench
(VERDICT weak #1 / ADVICE high). Interpreter tests cannot catch Mosaic
layout errors — only compiling on the real target can. This file compiles
every Pallas kernel the bench can dispatch, at the bench's production
shapes, in a SUBPROCESS (the suite pins this process to CPU in conftest.py,
and jax platforms can't be re-selected after backend init).

Skips cleanly when no TPU is attached.
"""

import json
import os
import subprocess
import sys

import pytest

# Cheap discovery, run first under a PARENT-side deadline: a
# site-initialized TPU plugin with no reachable TPU blocks ~8 minutes
# inside jax.devices() in a C call no in-process SIGALRM handler can
# interrupt (measured: a 120 s alarm printed only after the full 462 s
# wait), so only killing the subprocess from outside bounds it. A real
# attached TPU initializes well inside the window (jax itself warns at
# 60 s that init is unusually slow; 100 s leaves 40 s past that warn
# point). On tunneled runtimes with an unreachable TPU this deadline is
# paid IN FULL on every suite run, so it prices directly against the
# tier-1 870 s budget (ROADMAP) — keep it as tight as a slow real init
# allows.
_DISCOVER = r"""
import json, sys
import jax
try:
    print(json.dumps({"platform": jax.devices()[0].platform}))
except Exception as e:
    print(json.dumps({"skip": str(e)[:200]}))
"""

_PROBE = r"""
import json, sys
import jax
try:
    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(json.dumps({"skip": f"platform {dev.platform}"}))
        sys.exit(0)
except Exception as e:
    print(json.dumps({"skip": str(e)[:200]}))
    sys.exit(0)

import jax.numpy as jnp
from mpi4dl_tpu.ops import wgrad_pallas

# ResNet-110 @1024px bs=2 wgrad shapes (stem + the three stages) and the
# AmoebaNet-ish 2048px stem shape. supported() must admit them and the
# compile probe must succeed — a False from either is a regression.
cases = [
    ((2, 1026, 1026, 3), (2, 1024, 1024, 16)),
    ((2, 1026, 1026, 16), (2, 1024, 1024, 16)),
    ((2, 514, 514, 32), (2, 512, 512, 32)),
    ((2, 258, 258, 64), (2, 256, 256, 64)),
]
results = {}
for xp_shape, dy_shape in cases:
    ok = wgrad_pallas.supported(xp_shape, dy_shape, 3, 3)
    if ok:
        ok = wgrad_pallas._compiles(
            xp_shape, dy_shape, "bfloat16", "bfloat16", 3, 3
        )
    results[str(xp_shape[-1]) + "@" + str(dy_shape[1])] = bool(ok)
print(json.dumps({"results": results}))
"""


@pytest.mark.tpu_smoke
def test_pallas_kernels_compile_on_tpu():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the real platform win
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        found = subprocess.run(
            [sys.executable, "-c", _DISCOVER],
            capture_output=True, text=True, timeout=100, env=env, cwd=repo,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("device discovery exceeded 100s (no reachable TPU)")
    lines = [l for l in found.stdout.strip().splitlines() if l.startswith("{")]
    info = json.loads(lines[-1]) if lines else {}
    if info.get("platform") != "tpu":
        pytest.skip(f"no TPU: {info.get('skip') or info.get('platform')}")
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=repo,
    )
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no probe output; stderr: {proc.stderr[-2000:]}"
    out = json.loads(lines[-1])
    if "skip" in out:
        pytest.skip(f"no TPU: {out['skip']}")
    bad = {k: v for k, v in out["results"].items() if not v}
    assert not bad, (
        f"Pallas wgrad failed to compile on TPU for {sorted(bad)} — "
        "the bench will silently fall back to the slow XLA wgrad"
    )
