"""Telemetry federation + distributed tracing (ISSUE 6 tentpole):
snapshot-merge goldens (counters summed, histograms bucket-wise,
per-replica gauges + rollups), the federated registry protocol under the
windows/SLO/autoscale stack run FLEET-WIDE unchanged, phase attribution
on latency alerts, the Chrome-trace exporter joining span segments across
processes, and the live two-replica drill: two spawned engine processes +
an aggregator, merged counters golden-checked against the children, one
request's client+engine spans joined under a single trace id.
"""

import json
import os
import select
import subprocess
import sys
import time
import urllib.request

import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.telemetry.alerts import phase_attribution
from mpi4dl_tpu.telemetry.federation import (
    FederatedAggregator,
    FederatedRegistry,
    ReplicaTarget,
    merge_snapshots,
    trace_export_main,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _child(served=0, rejected=0, depth=0.0, latencies=()):
    reg = telemetry.MetricsRegistry()
    c = telemetry.declare(reg, "serve_requests_total")
    if served:
        c.inc(served, outcome="served")
    if rejected:
        c.inc(rejected, outcome="rejected_queue_full")
    telemetry.declare(reg, "serve_queue_depth").set(depth)
    h = telemetry.declare(reg, "serve_request_latency_seconds")
    for v in latencies:
        h.observe(v)
    return reg


# -- merge goldens ------------------------------------------------------------


def test_merge_counters_summed_histograms_bucketwise_gauges_per_replica():
    a = _child(served=90, rejected=10, depth=4, latencies=[0.004, 0.04])
    b = _child(served=100, depth=10, latencies=[0.4])
    merged, conflicts = merge_snapshots(
        {"r0": a.snapshot(), "r1": b.snapshot()}
    )
    assert conflicts == []

    # Counters: summed per label set, no replica label injected.
    c = {
        tuple(sorted(s["labels"].items())): s["value"]
        for s in merged["serve_requests_total"]["series"]
    }
    assert c[(("outcome", "served"),)] == 190
    assert c[(("outcome", "rejected_queue_full"),)] == 10
    assert merged["serve_requests_total"]["labels"] == ["outcome"]

    # Gauges: one series per replica + min/max/sum rollups.
    g = {
        s["labels"]["replica"]: s["value"]
        for s in merged["serve_queue_depth"]["series"]
    }
    assert g == {"r0": 4, "r1": 10, "sum": 14, "min": 4, "max": 10}
    assert merged["serve_queue_depth"]["labels"] == ["replica"]

    # Histograms: bucket-wise merge — counts, sums, and every cumulative
    # le bucket add exactly (percentile math over the merge is exact).
    (h,) = merged["serve_request_latency_seconds"]["series"]
    assert h["count"] == 3
    assert h["sum"] == pytest.approx(0.444)
    ha = a.get("serve_request_latency_seconds").snapshot_series()[0]
    hb = b.get("serve_request_latency_seconds").snapshot_series()[0]
    for le, n in h["buckets"].items():
        assert n == ha["buckets"][le] + hb["buckets"][le], le


def test_merge_conflicting_series_skipped_not_missummed():
    a = telemetry.MetricsRegistry()
    a.counter("m_total", "x", labels=("k",)).inc(1, k="v")
    b = telemetry.MetricsRegistry()
    b.gauge("m_total", "x").set(5)  # same name, different type
    merged, conflicts = merge_snapshots(
        {"r0": a.snapshot(), "r1": b.snapshot()}
    )
    assert len(conflicts) == 1 and "r1:m_total" in conflicts[0]
    assert merged["m_total"]["type"] == "counter"
    assert merged["m_total"]["series"][0]["value"] == 1


def test_reserved_replica_names_refused():
    for bad in ("sum", "min", "max", "", "spaced name"):
        with pytest.raises(ValueError):
            ReplicaTarget(bad, "http://x")


# -- federated registry protocol ---------------------------------------------


def test_federated_registry_local_overlay_wins_and_views_work():
    fed = FederatedRegistry()
    merged, _ = merge_snapshots({"r0": _child(
        served=8, latencies=[0.004, 0.004, 0.4]
    ).snapshot()})
    fed.set_merged(merged)
    # declare() writes land on the local layer through the same protocol.
    telemetry.declare(fed, "federation_replicas").set(1, state="up")
    snap = fed.snapshot()
    assert "serve_requests_total" in snap and "federation_replicas" in snap
    # Merged metric views answer the cumulative-SLI protocol.
    view = fed.get("serve_request_latency_seconds")
    assert view.kind == "histogram"
    assert view.buckets  # parsed float bounds for threshold resolution
    from mpi4dl_tpu.telemetry.slo import cumulative_sli, latency_objective

    sli = cumulative_sli(fed, latency_objective(0.99, threshold_s=0.005))
    assert sli == pytest.approx(2 / 3)
    # Local name shadows a merged one.
    fed.gauge("serve_queue_depth", "local").set(99)
    assert fed.get("serve_queue_depth").value() == 99
    assert fed.snapshot()["serve_queue_depth"]["series"][0]["value"] == 99


def test_windows_fall_back_to_replica_sum_rollup():
    """The autoscaler's unlabeled serve_queue_depth lookup answers with
    the FLEET total against a federated snapshot — the fallback that
    lets it run fleet-wide unchanged."""
    fed = FederatedRegistry()
    w = telemetry.SnapshotWindow(fed, clock=lambda: 0)
    for t, (d0, d1) in enumerate(((4, 10), (6, 12))):
        merged, _ = merge_snapshots({
            "r0": _child(depth=d0, served=10 * (t + 1)).snapshot(),
            "r1": _child(depth=d1, served=5 * (t + 1)).snapshot(),
        })
        fed.set_merged(merged)
        w.record(float(t * 10))
    assert w.value("serve_queue_depth") == 18  # 6 + 12
    assert w.mean_gauge("serve_queue_depth", 100.0) == pytest.approx(16.0)
    # Counters merged without replica labels: increase() is fleet-wide.
    assert w.increase("serve_requests_total", 100.0, outcome="served") == 15


# -- fleet-wide SLO evaluation ------------------------------------------------


def test_fleet_slo_and_autoscaler_over_live_replicas():
    """Two in-process 'replicas' behind real /snapshotz endpoints: the
    aggregator merges them and the UNCHANGED SLOEvaluator + Autoscaler
    compute fleet-wide burn and a rising desired-replica count."""
    r = [_child(served=100), _child(served=100)]
    servers = [telemetry.MetricsServer(x, port=0) for x in r]
    agg = FederatedAggregator(
        replicas={
            f"r{i}": f"http://127.0.0.1:{s.port}"
            for i, s in enumerate(servers)
        },
        slo=telemetry.SLOConfig(availability=0.999, interval_s=1.0),
        queue_capacity=128,
        clock=lambda: 0,
    )
    try:
        agg.scrape_once(now=0.0)
        # Replica 0 starts rejecting hard; replica 1 stays clean.
        telemetry.declare(r[0], "serve_requests_total").inc(
            50, outcome="rejected_queue_full"
        )
        agg.scrape_once(now=30.0)
        burn = agg.registry.get("slo_burn_rate").value(
            slo="availability", window="fast_long", tenant="default"
        )
        assert burn is not None and burn > 14.4  # fleet-wide page burn
        fired = agg.registry.get("alert_active").value(
            alert="availability_fast_burn", severity="page"
        )
        assert fired == 1.0
        assert (
            agg.registry.get("autoscale_desired_replicas").value() == 2
        )  # pressure: fleet rejections
        # Per-replica scrape accounting.
        assert agg.registry.get("federation_replicas").value(state="up") == 2
        assert agg.registry.get("federation_scrapes_total").value(
            replica="r0", outcome="ok"
        ) == 2
        # The federated server re-exposes the merged view + fleet alerts.
        srv = agg.serve(port=0)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=10
        ).read().decode()
        assert 'serve_queue_depth{replica="r0"}' in body
        assert "slo_burn_rate" in body
        alertz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/alertz", timeout=10
        ).read())
        assert any(
            a["name"] == "availability_fast_burn" and a["state"] == "firing"
            for a in alertz["alerts"]
        )
    finally:
        agg.close()
        for s in servers:
            s.close()


def test_aggregator_down_replica_counted_and_health_degrades():
    reg = _child(served=5)
    srv = telemetry.MetricsServer(reg, port=0)
    dead_port = srv.port  # will be closed below — guaranteed-dead target
    agg = FederatedAggregator(replicas={
        "up0": f"http://127.0.0.1:{srv.port}",
    })
    try:
        agg.scrape_once()
        assert agg.health_snapshot()["healthy"]
        srv.close()
        agg.add_replica("down0", f"http://127.0.0.1:{dead_port}")
        agg.scrape_once()
        h = agg.health_snapshot()
        assert not h["healthy"] and "down0" in h["reason"]
        assert agg.registry.get("federation_scrapes_total").value(
            replica="down0", outcome="error"
        ) >= 1
        # The up replica's LAST snapshot stays in the merge (stale, not
        # vanished — vanishing would read as a counter restart).
        assert agg.registry.get("serve_requests_total").value(
            outcome="served"
        ) == 5
    finally:
        agg.close()


# -- phase attribution on latency alerts --------------------------------------


def test_latency_alert_transition_carries_phase_attribution():
    """A forced latency regression (queue_wait share explodes) fires the
    latency alert WITH an attribution payload naming the regressed
    phase — the ISSUE acceptance drill's alerting half, deterministic."""
    reg = telemetry.MetricsRegistry()
    spans = telemetry.declare(reg, "serve_span_seconds")
    lat = telemetry.declare(reg, "serve_request_latency_seconds")

    def serve(n, queue_s, compute_s):
        for _ in range(n):
            spans.observe(queue_s, phase="queue_wait")
            spans.observe(compute_s, phase="device_compute")
            lat.observe(queue_s + compute_s)

    cfg = telemetry.SLOConfig(
        latency_threshold_s=0.025, latency_target=0.99, interval_s=1.0
    )
    ev = telemetry.SLOEvaluator(
        registry=reg, objectives=cfg.objectives(), config=cfg,
        clock=lambda: 0, start=False,
    )
    serve(200, 0.002, 0.008)          # healthy baseline: 10 ms e2e
    ev.evaluate_once(now=0.0)
    serve(100, 0.050, 0.008)          # regression: queue wait x25
    ev.evaluate_once(now=30.0)
    fired = [a for a in ev.alerts.values() if a.state == "firing"]
    assert any(a.name == "latency_fast_burn" for a in fired)
    trans = [
        t for t in ev.transitions
        if t["attrs"]["alert"] == "latency_fast_burn"
        and t["attrs"]["to"] == "firing"
    ]
    pa = trans[-1]["attrs"]["phase_attribution"]
    assert pa["regressed_phase"] == "queue_wait"
    assert pa["delta"]["queue_wait"] > 0.5
    assert ev.last_phase_attribution["alert"].startswith("latency_")
    assert ev.state()["phase_attribution"]["regressed_phase"] == "queue_wait"
    # Transitions stay schema-valid with the payload attached.
    telemetry.validate_event(trans[-1])


# -- chrome-trace export ------------------------------------------------------


def _cross_process_events():
    client = telemetry.span_event(
        "client.request", "trace-a",
        telemetry.spans_from_marks(
            [("issue", 10.0), ("client_submit", 10.05), ("client_wait", 10.9)]
        ),
        attrs={"pid": 111, "role": "client"}, ts=1000.9,
    )
    engine = telemetry.span_event(
        "serve.request", "trace-a",
        telemetry.spans_from_marks([
            ("submit", 5.0), ("queue_wait", 5.2), ("batch_form", 5.25),
            ("h2d_stage", 5.3), ("device_compute", 5.8),
        ]),
        attrs={"pid": 222, "role": "engine"}, ts=1000.85,
    )
    other = telemetry.span_event(
        "serve.request", "trace-b",
        telemetry.spans_from_marks([("submit", 6.0), ("device_compute", 6.1)]),
        attrs={"pid": 222, "role": "engine"}, ts=1001.0,
    )
    return [client, engine, other]


def test_chrome_trace_joins_processes_under_one_trace_id():
    events = _cross_process_events()
    groups = telemetry.group_spans_by_trace(events)
    assert set(groups) == {"trace-a", "trace-b"}
    assert len(groups["trace-a"]) == 2

    doc = telemetry.chrome_trace(events, trace_id="trace-a")
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {111, 222}
    assert all(e["args"]["trace_id"] == "trace-a" for e in xs)
    names = {e["name"] for e in xs}
    assert {"client_wait", "queue_wait", "device_compute"} <= names
    # Wall-clock alignment: the engine segment sits INSIDE the client's
    # issue→resolve window (client issued at wall 1000.0, engine submit
    # at 1000.05, both normalized against the same t0).
    client_start = min(e["ts"] for e in xs if e["pid"] == 111)
    engine_start = min(e["ts"] for e in xs if e["pid"] == 222)
    assert client_start == 0.0
    assert engine_start == pytest.approx(0.05e6)
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta} == {"client", "engine"}
    # No filter: both traces export.
    assert len(telemetry.chrome_trace(events)["traceEvents"]) > len(
        doc["traceEvents"]
    )


def test_trace_export_cli_roundtrip(tmp_path, capsys):
    log = tmp_path / "telemetry-1.jsonl"
    with open(log, "w") as f:
        for ev in _cross_process_events():
            f.write(json.dumps(ev) + "\n")
    out = tmp_path / "trace.json"
    rc = trace_export_main(
        [str(log), "--trace-id", "trace-a", "-o", str(out)]
    )
    assert rc == 0
    doc = json.load(open(out))
    assert {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"} == {111, 222}
    rc = trace_export_main([str(tmp_path), "--list"])
    assert rc == 0
    listed = capsys.readouterr().out
    assert "trace-a" in listed and "trace-b" in listed
    # Unknown trace id → loud nonzero, not an empty file.
    assert trace_export_main([str(log), "--trace-id", "nope"]) == 1


# -- the live two-replica drill ----------------------------------------------


def _read_stdout_line(proc, prefix, deadline):
    """Timeout-guarded readline: the drill must fail loudly, not hang
    tier-1, if a replica never comes up."""
    while time.monotonic() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [], 0.5)
        if not ready:
            if proc.poll() is not None:
                raise AssertionError(
                    f"replica died rc={proc.returncode}: "
                    f"{proc.stderr.read()[-2000:]}"
                )
            continue
        line = proc.stdout.readline()
        if not line:
            raise AssertionError(
                f"replica stdout closed: {proc.stderr.read()[-2000:]}"
            )
        if line.startswith(prefix):
            return line.strip()
    raise AssertionError(f"timed out waiting for {prefix!r}")


def test_two_replica_federation_smoke(tmp_path):
    """ISSUE CI satellite: spawn two engine processes, federate their
    /snapshotz endpoints, golden-check the merged counters against the
    children, and join one request's client+replica span segments under
    a single trace id in the exported Chrome trace."""
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    tele_dir = tmp_path / "tele"
    n_per_replica = 3
    procs = []
    try:
        for _ in range(2):
            procs.append(subprocess.Popen(
                [sys.executable,
                 os.path.join(REPO, "tests", "_replica_worker.py"),
                 str(tele_dir)],
                env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            ))
        deadline = time.monotonic() + 420  # CPU compile dominates
        ports = [
            int(_read_stdout_line(p, "PORT ", deadline).split()[1])
            for p in procs
        ]

        # The parent is the CLIENT process: it mints the trace ids, logs
        # its own client.request segments, and hands the ids across the
        # process hop (stdin here; the fleet router's RPC tomorrow).
        client_log = telemetry.JsonlWriter(str(tmp_path / "client"))
        trace_ids = [
            [telemetry.new_trace_id("client") for _ in range(n_per_replica)]
            for _ in procs
        ]
        for p, ids in zip(procs, trace_ids):
            for tid in ids:
                t0 = time.monotonic()
                p.stdin.write(tid + "\n")
                p.stdin.flush()
                client_log.write(telemetry.span_event(
                    "client.request", tid,
                    telemetry.spans_from_marks([
                        ("issue", t0), ("client_submit", time.monotonic()),
                    ]),
                    attrs={"pid": os.getpid(), "role": "client"},
                ))

        # Federate while both replicas are live.
        agg = FederatedAggregator(replicas={
            f"r{i}": f"http://127.0.0.1:{port}"
            for i, port in enumerate(ports)
        })
        # Children scrape their requests' completion asynchronously; poll
        # (timeout-guarded) until the fleet-wide served counter converges.
        want = 2 * n_per_replica
        while time.monotonic() < deadline:
            agg.scrape_once()
            got = agg.registry.get("serve_requests_total")
            if got is not None and got.value(outcome="served") == want:
                break
            time.sleep(0.2)

        # Golden-check the merge against the children's own /snapshotz.
        child_served = []
        for port in ports:
            snap = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/snapshotz", timeout=10
            ).read())
            telemetry.validate_event(snap)  # the schema federation trusts
            child_served.append(sum(
                s["value"]
                for s in snap["metrics"]["serve_requests_total"]["series"]
                if s["labels"]["outcome"] == "served"
            ))
        assert child_served == [n_per_replica, n_per_replica]
        assert agg.registry.get("serve_requests_total").value(
            outcome="served"
        ) == sum(child_served)
        # Per-replica-labeled gauges survived the merge.
        depth = {
            s["labels"]["replica"]: s["value"]
            for s in agg.registry.get("serve_queue_depth").snapshot_series()
        }
        assert {"r0", "r1", "sum", "min", "max"} <= set(depth)
        assert agg.registry.get("federation_replicas").value(state="up") == 2

        for p in procs:
            p.stdin.write("DONE\n")
            p.stdin.close()
        for p in procs:
            assert "SERVED" in _read_stdout_line(p, "SERVED", deadline)
            assert p.wait(timeout=60) == 0
        client_log.close()

        # One request's spans join across the process hop: client segment
        # from THIS pid, engine lifecycle from the replica's pid, one id.
        events = []
        for d in (tmp_path / "client", tele_dir):
            for f in os.listdir(d):
                events.extend(telemetry.read_events(str(d / f)))
        tid = trace_ids[0][0]
        doc = telemetry.chrome_trace(events, trace_id=tid)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        pids = {e["pid"] for e in xs}
        assert os.getpid() in pids and procs[0].pid in pids
        names = {e["name"] for e in xs}
        assert {"client_submit", "queue_wait", "batch_form",
                "h2d_stage", "device_compute"} <= names
        # Every replica's engine segment carries the propagated ids, and
        # ids never collide across the two processes' own minting.
        groups = telemetry.group_spans_by_trace(events)
        for ids in trace_ids:
            for t in ids:
                assert t in groups
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
