"""Unit tests for the static HLO analyzer (:mod:`mpi4dl_tpu.analysis`) on
canned HLO snippets — parser, inventory, bytes-moved, start→done overlap
distance, and every lint rule — plus the analyzer CLI on a real (tiny)
compiled program. None of the canned tests compile a model; they are the
cheap tier-1 tripwire the ISSUE's acceptance criteria require: a synthetic
HLO with an unoverlapped collective or a stray all-to-all MUST produce
error-severity findings."""

import json

import pytest

from mpi4dl_tpu.analysis import (
    Expectations,
    analyze_hlo_text,
    collective_inventory,
    collective_records,
    max_severity,
    overlap_summary,
    parse_hlo_text,
)
from mpi4dl_tpu.analysis.hlo import parse_shape
from mpi4dl_tpu.analysis.rules import LintContext, run_rules

# A scheduled module with one async (start/done) all-reduce whose window
# contains real compute (a fusion and a convolution), one sync
# collective-permute, and operand USES that must not be counted as defs.
OVERLAPPED = """\
HloModule overlapped, is_scheduled=true

%fused_computation (param_0.1: f32[8,128]) -> f32[8,128] {
  %param_0.1 = f32[8,128]{1,0} parameter(0)
  ROOT %mul.1 = f32[8,128]{1,0} multiply(f32[8,128]{1,0} %param_0.1, f32[8,128]{1,0} %param_0.1)
}

ENTRY %main.1 (p0: f32[8,128], p1: f32[2,16,16,4]) -> f32[8,128] {
  %p0 = f32[8,128]{1,0} parameter(0)
  %p1 = f32[2,16,16,4]{3,2,1,0} parameter(1)
  %ar-start.1 = f32[8,128]{1,0} all-reduce-start(f32[8,128]{1,0} %p0), channel_id=1, replica_groups={{0,1}}, to_apply=%add
  %fusion.1 = f32[8,128]{1,0} fusion(f32[8,128]{1,0} %p0), kind=kLoop, calls=%fused_computation
  %convolution.1 = f32[2,16,16,4]{3,2,1,0} convolution(f32[2,16,16,4]{3,2,1,0} %p1, f32[2,16,16,4]{3,2,1,0} %p1), window={size=1x1}, dim_labels=b01f_01io->b01f
  %cp.1 = f32[8,128]{1,0} collective-permute(f32[8,128]{1,0} %fusion.1), channel_id=2, source_target_pairs={{0,1},{1,0}}
  ROOT %ar-done.1 = f32[8,128]{1,0} all-reduce-done(f32[8,128]{1,0} %ar-start.1)
}
"""

# Same module but with the all-reduce window empty (start immediately
# followed by done) and a payload over the 1 MiB noise threshold: the
# statically-visible lost-overlap signature. Also carries a stray
# all-to-all.
BAD = """\
HloModule bad, is_scheduled=true

ENTRY %main.1 (p0: f32[512,1024]) -> f32[512,1024] {
  %p0 = f32[512,1024]{1,0} parameter(0)
  %ar-start.1 = f32[512,1024]{1,0} all-reduce-start(f32[512,1024]{1,0} %p0), channel_id=1, replica_groups={{0,1}}, to_apply=%add
  %ar-done.1 = f32[512,1024]{1,0} all-reduce-done(f32[512,1024]{1,0} %ar-start.1)
  ROOT %a2a.1 = f32[512,1024]{1,0} all-to-all(f32[512,1024]{1,0} %ar-done.1), channel_id=2, replica_groups={{0,1}}, dimensions={0}
}
"""


def test_parse_shapes():
    s, rest = parse_shape("f32[4,16,8,32]{3,2,0,1} all-gather(...)")
    assert s.dtype == "f32" and s.dims == (4, 16, 8, 32)
    assert s.byte_size() == 4 * 16 * 8 * 32 * 4
    assert rest.lstrip().startswith("all-gather")
    t, _ = parse_shape("(f32[8,128]{1,0}, u32[2]{0}, pred[])")
    assert t.is_tuple and len(t.elements) == 3
    assert t.byte_size() == 8 * 128 * 4 + 2 * 4 + 1
    scalar, _ = parse_shape("bf16[] add(...)")
    assert scalar.dims == () and scalar.byte_size() == 2


def test_parser_structure():
    mod = parse_hlo_text(OVERLAPPED)
    assert mod.name == "overlapped" and mod.is_scheduled
    assert set(mod.computations) == {"fused_computation", "main.1"}
    assert mod.entry.name == "main.1"
    ops = [i.opcode for i in mod.entry]
    assert ops == [
        "parameter", "parameter", "all-reduce-start", "fusion",
        "convolution", "collective-permute", "all-reduce-done",
    ]
    done = mod.entry.instructions[-1]
    assert done.is_root and done.operands == ("ar-start.1",)
    assert mod.entry.instructions[2].channel_id == 1


def test_inventory_counts_defs_not_uses():
    inv = collective_inventory(OVERLAPPED)
    # start+done is ONE all-reduce; the done's operand use of %ar-start.1
    # and the permute's operand use of %fusion.1 count nothing.
    assert inv["all-reduce"] == 1
    assert inv["collective-permute"] == 1
    assert inv["all-to-all"] == 0


def test_overlap_distance_and_bytes():
    recs = collective_records(OVERLAPPED)
    ar = next(r for r in recs if r.opcode == "all-reduce")
    assert ar.is_async and ar.done_name == "ar-done.1"
    # fusion, convolution, collective-permute sit between start and done.
    assert ar.distance == 3
    assert ar.compute_between == 2  # fusion + convolution; permute is comms
    assert ar.bytes_moved == 8 * 128 * 4
    cp = next(r for r in recs if r.opcode == "collective-permute")
    assert not cp.is_async and cp.distance is None
    summary = overlap_summary(recs)
    assert summary["async_pairs"] == 1
    assert summary["zero_overlap"] == []
    assert summary["bytes_by_op"]["all-reduce"] == 4096


def test_clean_module_lints_clean():
    report = analyze_hlo_text(OVERLAPPED)
    assert report.max_severity is None and report.ok


def test_zero_overlap_and_stray_all_to_all_fail_the_lint():
    """The ISSUE acceptance criterion: synthetic HLO with an unoverlapped
    collective or a stray all-to-all must produce error findings."""
    report = analyze_hlo_text(BAD)
    rules_hit = {f["rule"] for f in report.findings if f["severity"] == "error"}
    assert "zero-overlap-collective" in rules_hit
    assert "stray-all-to-all" in rules_hit
    assert not report.ok and report.max_severity == "error"


def test_zero_overlap_below_noise_threshold_is_warn():
    small = BAD.replace("512,1024", "8,16").replace(
        "ROOT %a2a.1 = f32[8,16]{1,0} all-to-all(f32[8,16]{1,0} %ar-done.1), channel_id=2, replica_groups={{0,1}}, dimensions={0}",
        "ROOT %n.1 = f32[8,16]{1,0} negate(f32[8,16]{1,0} %ar-done.1)",
    )
    report = analyze_hlo_text(small)
    zo = [f for f in report.findings if f["rule"] == "zero-overlap-collective"]
    assert zo and all(f["severity"] == "warn" for f in zo)


def test_pure_dp_rule_flags_resharding():
    report = analyze_hlo_text(OVERLAPPED, expected=Expectations(pure_dp=True))
    assert any(
        f["rule"] == "stray-resharding" and f["severity"] == "error"
        for f in report.findings
    )  # the collective-permute is illegal in a pure-DP program


def test_single_chip_rule_flags_any_collective():
    # The serving gate: a single-chip program may not communicate at all —
    # even the all-reduce that pure_dp would bless is an error here.
    report = analyze_hlo_text(
        OVERLAPPED, expected=Expectations(single_chip=True)
    )
    assert any(
        f["rule"] == "single-chip-collectives" and f["severity"] == "error"
        for f in report.findings
    )
    # "all-reduce" and "collective-permute" both named in the message.
    msg = next(
        f["message"] for f in report.findings
        if f["rule"] == "single-chip-collectives"
    )
    assert "all-reduce" in msg and "collective-permute" in msg


def test_single_chip_rule_passes_collective_free_hlo():
    clean = """\
HloModule clean, is_scheduled=true

ENTRY %main.1 (p0: f32[8,128]) -> f32[8,128] {
  ROOT %p0 = f32[8,128]{1,0} parameter(0)
}
"""
    report = analyze_hlo_text(clean, expected=Expectations(single_chip=True))
    assert not any(
        f["rule"] == "single-chip-collectives" for f in report.findings
    )


def test_halo_permute_window():
    # OVERLAPPED has exactly 1 collective-permute.
    ok = analyze_hlo_text(OVERLAPPED, expected=Expectations(halo_shifts=1))
    assert not any(f["rule"] == "halo-permute-count" for f in ok.findings)
    low = analyze_hlo_text(OVERLAPPED, expected=Expectations(halo_shifts=4))
    assert any(
        f["rule"] == "halo-permute-count" and f["severity"] == "error"
        for f in low.findings
    )
    # halo_shifts=0 derives a ceiling of 0 permutes (+extra widens it).
    high = analyze_hlo_text(OVERLAPPED, expected=Expectations(halo_shifts=0))
    assert any(f["rule"] == "halo-permute-count" for f in high.findings)
    widened = analyze_hlo_text(
        OVERLAPPED, expected=Expectations(halo_shifts=0, extra_permutes=1)
    )
    assert not any(
        f["rule"] == "halo-permute-count" for f in widened.findings
    )


def test_pipeline_permute_budget_shifts_window_and_is_named():
    """ISSUE 14 CI satellite: ``extra_permutes`` is the pipeline engine's
    EXACT stage-boundary permute budget — it shifts BOTH window bounds
    (a pure-LP pipeline is gated at exactly the budget), and both the
    floor and ceiling messages name the budget so a finding reads as
    "the pipeline wires changed", not as mystery halo math."""
    # OVERLAPPED has exactly 1 collective-permute. Budget 1, zero halo
    # shifts: window [1, 1] — clean.
    exact = analyze_hlo_text(
        OVERLAPPED, expected=Expectations(halo_shifts=0, extra_permutes=1)
    )
    assert not any(
        f["rule"] == "halo-permute-count" for f in exact.findings
    )
    # Budget 2 with only 1 permute: the FLOOR trips (a dropped pipeline
    # wire is as much a bug as a doubled one) and names the budget.
    dropped = analyze_hlo_text(
        OVERLAPPED, expected=Expectations(halo_shifts=0, extra_permutes=2)
    )
    low = [f for f in dropped.findings if f["rule"] == "halo-permute-count"]
    assert low and low[0]["severity"] == "error"
    assert "pipeline permute budget of 2" in low[0]["message"]
    # Three permutes against a budget of 2: the CEILING names it too.
    tripled = OVERLAPPED.replace(
        "ROOT %ar-done.1 = f32[8,128]{1,0} all-reduce-done(f32[8,128]{1,0} %ar-start.1)",
        "%cp.2 = f32[8,128]{1,0} collective-permute(f32[8,128]{1,0} %fusion.1), channel_id=3, source_target_pairs={{0,1},{1,0}}\n"
        "  %cp.3 = f32[8,128]{1,0} collective-permute(f32[8,128]{1,0} %fusion.1), channel_id=4, source_target_pairs={{0,1},{1,0}}\n"
        "  ROOT %ar-done.1 = f32[8,128]{1,0} all-reduce-done(f32[8,128]{1,0} %ar-start.1)",
    )
    over = analyze_hlo_text(
        tripled, expected=Expectations(halo_shifts=0, extra_permutes=2)
    )
    high = [f for f in over.findings if f["rule"] == "halo-permute-count"]
    assert high and high[0]["severity"] == "error"
    assert "pipeline permute budget of 2" in high[0]["message"]


def test_memory_regression_rule():
    mem = {"peak_bytes": 1_100_000, "baseline_bytes": 1_000_000,
           "tolerance": 0.05}
    report = analyze_hlo_text(OVERLAPPED, memory=mem)
    assert any(
        f["rule"] == "peak-memory-regression" and f["severity"] == "error"
        for f in report.findings
    )
    mem_ok = dict(mem, peak_bytes=1_010_000)
    report = analyze_hlo_text(OVERLAPPED, memory=mem_ok)
    assert not any(
        f["severity"] == "error" for f in report.findings
    )
    no_base = {"peak_bytes": 123}
    report = analyze_hlo_text(OVERLAPPED, memory=no_base)
    assert any(
        f["rule"] == "peak-memory-regression" and f["severity"] == "info"
        for f in report.findings
    )


def test_remat_effectiveness_rule():
    ineffective = {"policy": "scanq", "store_budget_mb": 100,
                   "granted_bytes": 0, "grants": {}}
    report = analyze_hlo_text(OVERLAPPED, remat=ineffective)
    assert any(
        f["rule"] == "remat-effectiveness" and f["severity"] == "warn"
        for f in report.findings
    )
    overgrant = {"policy": "scanq", "store_budget_mb": 1,
                 "granted_bytes": 50_000_000, "grants": {0: 50_000_000}}
    report = analyze_hlo_text(OVERLAPPED, remat=overgrant)
    assert any(
        f["rule"] == "remat-effectiveness" and f["severity"] == "error"
        for f in report.findings
    )


def test_report_json_round_trip(tmp_path):
    report = analyze_hlo_text(BAD, platform="cpu", config={"model": "canned"})
    blob = json.loads(report.to_json())
    assert blob["ok"] is False
    assert blob["inventory"]["all-to-all"] == 1
    assert blob["config"] == {"model": "canned"}
    assert blob["overlap"]["async_pairs"] == 1
    assert {f["rule"] for f in blob["findings"]} >= {
        "stray-all-to-all", "zero-overlap-collective",
    }


def test_max_severity_ordering():
    from mpi4dl_tpu.analysis.rules import Finding

    assert max_severity([]) is None
    fs = [Finding("r", "info", "m"), Finding("r", "warn", "m")]
    assert max_severity(fs) == "warn"
    fs.append(Finding("r", "error", "m"))
    assert max_severity(fs) == "error"


def test_cli_on_compiled_program(tmp_path, monkeypatch):
    """End-to-end: the analyzer CLI compiles the small spatial resnet on
    the test mesh, writes a JSON report with inventory + bytes + overlap
    + memory, and exits 0 (no error findings on the real engine)."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    from mpi4dl_tpu.analysis.cli import main

    out = tmp_path / "report.json"
    rc = main([
        "--model", "resnet", "--size", "32", "--batch", "4",
        "--json", str(out),
    ])
    assert rc == 0
    blob = json.loads(out.read_text())
    assert blob["ok"] is True
    assert blob["inventory"]["collective-permute"] == 36
    assert blob["config"]["halo_shifts"] == 20
    assert blob["overlap"]["total_bytes"] > 0
    assert all(r["bytes_moved"] > 0 for r in blob["collectives"])
    # memory_analysis works on the CPU backend, so peak must be present.
    assert blob["memory"]["peak_bytes"] > 0
