"""Halo exchange + conv compute micro-benchmark and numerical validation.

TPU rebuild of three reference scripts in one:

- ``benchmark_sp_halo_exchange_with_compute.py`` (exchange then conv on the
  padded tile, timed, ref ``:392-397``);
- ``benchmark_sp_halo_exchange_with_compute_val.py`` (distributed conv with
  weights/bias forced to 1.0 vs sequential full-image conv, ref
  ``:704-780``);
- ``benchmark_sp_halo_exchange_conv.py`` validation modes (full conv
  equality, ref ``:940-1092``).

The reference needed the weights-set-to-1.0 trick to separate exchange bugs
from cuDNN nondeterminism; XLA convs are deterministic, so we validate with
random weights at float tolerance AND with ones at exact equality.

On TPU the "overlap" question the reference's dead code asks
(``spatial.py:415-828``) is answered by the compiler: the exchange and the
conv are one fused XLA program, and XLA's latency-hiding scheduler overlaps
the collective with independent compute. This benchmark reports the fused
cost directly (compare with the exchange-only number from
``benchmark_sp_halo_exchange.py`` to see the overlap).
"""

import argparse
import functools
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", "..")
)


def get_args():
    p = argparse.ArgumentParser(description="halo exchange + conv (TPU-native)")
    p.add_argument("--image-size", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--num-filters", type=int, default=64)
    p.add_argument("--in-channels", type=int, default=3)
    p.add_argument("--num-spatial-parts", type=int, default=4)
    p.add_argument("--slice-method", type=str, default="square")
    p.add_argument("--halo-len", type=int, default=1, help="(kernel-1)/2")
    p.add_argument("--iterations", type=int, default=50)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--impl", type=str, default="xla", choices=["xla", "pallas"])
    p.add_argument("--skip-validation", action="store_true")
    return p.parse_args()


def main():
    args = get_args()

    from mpi4dl_tpu.utils import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpi4dl_tpu.config import tile_grid
    from mpi4dl_tpu.parallel.halo import halo_exchange

    th, tw = tile_grid(args.num_spatial_parts, args.slice_method)
    n = th * tw
    if len(jax.devices()) < n:
        sys.exit(
            f"need {n} devices; have {len(jax.devices())}. Set JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} to simulate."
        )
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(th, tw), ("tile_h", "tile_w"))
    spec = P(None, "tile_h", "tile_w", None)
    h = args.halo_len
    k = 2 * h + 1

    b, s, cin, cout = args.batch_size, args.image_size, args.in_channels, args.num_filters
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, s, s, cin)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((k, k, cin, cout)) * 0.05, jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, spec))

    dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, P()), out_specs=spec, check_vma=False
    )
    def dist_conv(x, w):
        p = halo_exchange(x, h, h, "tile_h", "tile_w", impl=args.impl)
        return lax.conv_general_dilated(p, w, (1, 1), "VALID", dimension_numbers=dn)

    @jax.jit
    def seq_conv(x, w):
        return lax.conv_general_dilated(
            x, w, (1, 1), ((h, h), (h, h)), dimension_numbers=dn
        )

    if not args.skip_validation:
        got = np.asarray(dist_conv(xs, w))
        want = np.asarray(seq_conv(x, w))
        err = np.max(np.abs(got - want))
        print(f"validation (random weights): max|err| = {err:.3e}")
        ones_w = jnp.ones_like(w)
        got1 = np.asarray(dist_conv(xs, ones_w))
        want1 = np.asarray(seq_conv(x, ones_w))
        exact = np.array_equal(got1, want1)
        print(f"validation (weights=1, ref parity trick): {'EXACT' if exact else 'FAILED'}")
        if err > 1e-4 or not exact:
            sys.exit(1)

    def bench(fn, *a):
        out = None
        for _ in range(args.warmup):
            out = fn(*a)
        if out is not None:
            jax.block_until_ready(out)
        times = []
        for _ in range(args.iterations):
            t0 = time.perf_counter()
            out = fn(*a)
            jax.block_until_ready(out)
            times.append((time.perf_counter() - t0) * 1e3)
        return statistics.mean(times), statistics.median(times)

    m, md = bench(dist_conv, xs, w)
    print(
        f"halo+conv[{args.impl}] {s}x{s} k={k} {args.slice_method} x{n}: "
        f"mean {m:.4f} ms  median {md:.4f} ms"
    )
    m2, md2 = bench(seq_conv, x, w)
    print(f"sequential full-image conv: mean {m2:.4f} ms  median {md2:.4f} ms")


if __name__ == "__main__":
    main()
