"""Numerical validation of the distributed (halo-exchanged) convolution.

TPU rebuild of reference
``benchmarks/communication/halo/benchmark_sp_halo_exchange_with_compute_val.py``:
weights AND bias forced to 1.0 on both the distributed and the sequential conv
(ref ``:704-706, :752-753`` — the trick that removed cuDNN nondeterminism from
the comparison), then two independent equality checks per tile (ref
``:727-780``):

1. the received halo ring vs an ``np.pad`` ground truth of the global image;
2. the distributed conv output vs the sequential full-image conv.

XLA convs are deterministic, so the 1.0-weights runs are checked with exact
integer-style equality, and an extra random-weights run is checked at float
tolerance (strictly stronger than the reference's validation).
"""

import argparse
import functools
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", "..")
)


def get_args():
    p = argparse.ArgumentParser(
        description="distributed conv validation, weights/bias = 1.0 (TPU-native)"
    )
    p.add_argument("--image-size", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--num-filters", type=int, default=8)
    p.add_argument("--in-channels", type=int, default=3)
    p.add_argument("--num-spatial-parts", type=int, default=4)
    p.add_argument("--slice-method", type=str, default="square")
    p.add_argument("--halo-len", type=int, default=1, help="(kernel-1)/2")
    p.add_argument("--impl", type=str, default="xla", choices=["xla", "pallas"])
    return p.parse_args()


def main():
    args = get_args()

    from mpi4dl_tpu.utils import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpi4dl_tpu.config import tile_grid
    from mpi4dl_tpu.parallel.halo import halo_exchange

    th, tw = tile_grid(args.num_spatial_parts, args.slice_method)
    n = th * tw
    if len(jax.devices()) < n:
        sys.exit(
            f"need {n} devices; have {len(jax.devices())}. Set JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} to simulate."
        )
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(th, tw), ("tile_h", "tile_w"))
    spec = P(None, "tile_h", "tile_w", None)
    h = args.halo_len
    k = 2 * h + 1

    b, s, cin, cout = (
        args.batch_size,
        args.image_size,
        args.in_channels,
        args.num_filters,
    )
    # Deterministic arange image (ref create_input, :417-470) so every check
    # is exact integer equality.
    x = jnp.arange(b * s * s * cin, dtype=jnp.float32).reshape(b, s, s, cin)
    xs = jax.device_put(x, NamedSharding(mesh, spec))
    w_shape = (k, k, cin, cout)
    dn = lax.conv_dimension_numbers(x.shape, w_shape, ("NHWC", "HWIO", "NHWC"))

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, P(), P()),
        out_specs=(spec, spec),
        check_vma=False,
    )
    def dist_conv_and_padded(x, w, bias):
        p = halo_exchange(x, h, h, "tile_h", "tile_w", impl=args.impl)
        y = (
            lax.conv_general_dilated(p, w, (1, 1), "VALID", dimension_numbers=dn)
            + bias
        )
        # Full padded tile (tiles evenly: every tile has the same padded
        # shape) so check 1 can validate the ENTIRE halo ring — all four
        # exchange directions and all boundary fills.
        return y, p

    @jax.jit
    def seq_conv(x, w, bias):
        return (
            lax.conv_general_dilated(
                x, w, (1, 1), ((h, h), (h, h)), dimension_numbers=dn
            )
            + bias
        )

    failures = 0

    # -- check 1: received halos vs np.pad ground truth (ref :727-748) -------
    ones_w = jnp.ones(w_shape, jnp.float32)
    ones_b = jnp.ones((cout,), jnp.float32)
    from halo_common import validate_padded_tiles

    got_y, got_pad = dist_conv_and_padded(xs, ones_w, ones_b)
    failures += validate_padded_tiles(got_pad, x, th, tw, h, h, label="halo")
    print(f"recv-halo validation: {'PASSED' if failures == 0 else 'FAILED'}")

    # -- check 2: conv output, weights/bias = 1.0, exact (ref :752-780) ------
    want_y = np.asarray(seq_conv(x, ones_w, ones_b))
    got_y = np.asarray(got_y)
    exact = np.array_equal(got_y, want_y)
    print(f"conv validation (weights=bias=1.0): {'EXACT' if exact else 'FAILED'}")
    if not exact:
        failures += 1

    # -- check 3: random weights at float tolerance (beyond the reference) ---
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal(w_shape) * 0.05, jnp.float32)
    bias = jnp.asarray(rng.standard_normal((cout,)), jnp.float32)
    xr = jnp.asarray(rng.standard_normal((b, s, s, cin)), jnp.float32)
    xrs = jax.device_put(xr, NamedSharding(mesh, spec))
    got_r, _ = dist_conv_and_padded(xrs, w, bias)
    err = np.max(np.abs(np.asarray(got_r) - np.asarray(seq_conv(xr, w, bias))))
    print(f"conv validation (random weights): max|err| = {err:.3e}")
    if err > 1e-4:
        failures += 1

    if failures:
        sys.exit(1)
    print("ALL VALIDATIONS PASSED")


if __name__ == "__main__":
    main()
