"""Shared ground-truth check for the halo benchmark scripts.

One implementation of the padded-tile equality loop (vs the reference, which
re-implements its ``np.pad`` harness in each of its four halo scripts,
``benchmark_sp_halo_exchange.py:417-584`` et al.).
"""

from __future__ import annotations

import sys

import numpy as np


def validate_padded_tiles(
    got_pad: np.ndarray,
    x: np.ndarray,
    th: int,
    tw: int,
    halo_h: int,
    halo_w: int,
    label: str = "recv",
) -> int:
    """Check every tile's FULL halo-carrying padded tile against the
    ``np.pad`` ground truth of the global image (all four exchange
    directions + boundary fill).

    got_pad: the shard_map output whose per-device value is the whole padded
        tile — globally ``[B, th*(t_h+2*halo_h), tw*(t_w+2*halo_w), C]``.
    x: the global input image ``[B, H, W, C]``.
    Returns the number of mismatching tiles (0 = pass), printing per-tile
    diagnostics to stderr.
    """
    x = np.asarray(x)
    got_pad = np.asarray(got_pad)
    s_h, s_w = x.shape[1], x.shape[2]
    t_h, t_w = s_h // th, s_w // tw
    p_h, p_w = t_h + 2 * halo_h, t_w + 2 * halo_w
    ref_pad = np.pad(x, ((0, 0), (halo_h, halo_h), (halo_w, halo_w), (0, 0)))
    bad = 0
    for i in range(th):
        for j in range(tw):
            # Tile (i,j)'s padded tile == the (t+2*halo)-window of the
            # globally padded image anchored at the tile origin.
            want = ref_pad[:, i * t_h : i * t_h + p_h, j * t_w : j * t_w + p_w, :]
            have = got_pad[:, i * p_h : (i + 1) * p_h, j * p_w : (j + 1) * p_w, :]
            if not np.array_equal(want, have):
                bad += 1
                print(f"{label} check tile ({i},{j}): MISMATCH", file=sys.stderr)
    return bad
