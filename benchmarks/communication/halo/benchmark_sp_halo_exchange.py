"""Raw halo-exchange micro-benchmark + validation.

TPU rebuild of reference
``benchmarks/communication/halo/benchmark_sp_halo_exchange.py`` (timing) and
its ``_val``/``_conv`` validation variants: a deterministic ``arange`` image
is tiled over the mesh, halo-exchanged, and every rank's received halos are
checked against an ``np.pad`` ground truth (ref ``create_input_*``
``:417-566``, ``test_output`` ``:570-584``); then the exchange alone is timed
(ref CUDA-event loop ``:587-620``; host wall-clock + ``block_until_ready``
here).

Flags: --image-size, --num-spatial-parts, --slice-method, --halo-len,
--iterations, --batch-size, --num-filters (channel count).
"""

import argparse
import functools
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", "..")
)


def get_args():
    p = argparse.ArgumentParser(description="halo exchange benchmark (TPU-native)")
    p.add_argument("--image-size", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--num-filters", type=int, default=3)
    p.add_argument("--num-spatial-parts", type=int, default=4)
    p.add_argument("--slice-method", type=str, default="square")
    p.add_argument("--halo-len", type=int, default=1)
    p.add_argument("--iterations", type=int, default=100)
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument(
        "--impl",
        type=str,
        default="xla",
        choices=["xla", "pallas"],
        help="xla = ppermute shifts; pallas = bidirectional remote-DMA kernel",
    )
    return p.parse_args()


def main():
    args = get_args()

    from mpi4dl_tpu.utils import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpi4dl_tpu.config import tile_grid
    from mpi4dl_tpu.parallel.halo import halo_exchange

    th, tw = tile_grid(args.num_spatial_parts, args.slice_method)
    n = th * tw
    if len(jax.devices()) < n:
        sys.exit(
            f"need {n} devices; have {len(jax.devices())}. Set JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} to simulate."
        )
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(th, tw), ("tile_h", "tile_w"))
    spec = P(None, "tile_h", "tile_w", None)
    h = args.halo_len

    b, s, c = args.batch_size, args.image_size, args.num_filters
    x = jnp.arange(b * s * s * c, dtype=jnp.float32).reshape(b, s, s, c)
    xs = jax.device_put(x, NamedSharding(mesh, spec))

    # -- validation vs np.pad ground truth (ref test_output, :570-584) -------
    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )
    def exchange_keep_halo(x):
        p = halo_exchange(x, h, h, "tile_h", "tile_w", impl=args.impl)
        # shard_map out shapes must tile evenly: crop the *interior overlap*
        # instead — each tile returns its padded tile's top-left corner of
        # tile size, i.e. rows/cols [0 : H_loc] of the padded tile.
        return p[:, : x.shape[1], : x.shape[2], :]

    got = np.asarray(exchange_keep_halo(xs))
    ref = np.pad(np.asarray(x), ((0, 0), (h, h), (h, h), (0, 0)))
    tile_h_sz, tile_w_sz = s // th, s // tw
    ok = True
    for i in range(th):
        for j in range(tw):
            # padded-tile top-left corner == global padded image at the tile's
            # origin (rows i*tile-h .. +tile, shifted by the pad offset).
            want = ref[:, i * tile_h_sz : i * tile_h_sz + tile_h_sz,
                       j * tile_w_sz : j * tile_w_sz + tile_w_sz, :]
            have = got[:, i * tile_h_sz : (i + 1) * tile_h_sz,
                       j * tile_w_sz : (j + 1) * tile_w_sz, :]
            if not np.array_equal(want, have):
                ok = False
                print(f"tile ({i},{j}): MISMATCH", file=sys.stderr)
    print(f"validation: {'PASSED' if ok else 'FAILED'}")
    if not ok:
        sys.exit(1)

    # -- timing (exchange_keep_halo: output depends on the received halos, so
    # XLA cannot dead-code-eliminate the collectives) -------------------------
    for _ in range(args.warmup):
        out = exchange_keep_halo(xs)
    jax.block_until_ready(out)
    times = []
    for _ in range(args.iterations):
        t0 = time.perf_counter()
        out = exchange_keep_halo(xs)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) * 1e3)
    print(
        f"halo exchange[{args.impl}] {s}x{s} halo={h} {args.slice_method} x{n}: "
        f"mean {statistics.mean(times):.4f} ms  median {statistics.median(times):.4f} ms"
    )


if __name__ == "__main__":
    main()
