"""Raw halo-exchange micro-benchmark + validation.

TPU rebuild of reference
``benchmarks/communication/halo/benchmark_sp_halo_exchange.py`` (timing) and
its ``_val``/``_conv`` validation variants: a deterministic ``arange`` image
is tiled over the mesh, halo-exchanged, and every rank's received halos are
checked against an ``np.pad`` ground truth (ref ``create_input_*``
``:417-566``, ``test_output`` ``:570-584``); then the exchange alone is timed
(ref CUDA-event loop ``:587-620``; host wall-clock + ``block_until_ready``
here).

Flags: --image-size, --num-spatial-parts, --slice-method, --halo-len,
--iterations, --batch-size, --num-filters (channel count).
"""

import argparse
import functools
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", "..")
)


def get_args():
    p = argparse.ArgumentParser(description="halo exchange benchmark (TPU-native)")
    p.add_argument("--image-size", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--num-filters", type=int, default=3)
    p.add_argument("--num-spatial-parts", type=int, default=4)
    p.add_argument("--slice-method", type=str, default="square")
    p.add_argument("--halo-len", type=int, default=1)
    p.add_argument("--iterations", type=int, default=100)
    p.add_argument("--warmup", type=int, default=10)
    p.add_argument(
        "--impl",
        type=str,
        default="xla",
        choices=["xla", "pallas"],
        help="xla = ppermute shifts; pallas = bidirectional remote-DMA kernel",
    )
    return p.parse_args()


def main():
    args = get_args()

    from mpi4dl_tpu.utils import apply_platform_env

    apply_platform_env()

    import jax
    import jax.numpy as jnp
    from mpi4dl_tpu.compat import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpi4dl_tpu.config import tile_grid
    from mpi4dl_tpu.parallel.halo import halo_exchange

    th, tw = tile_grid(args.num_spatial_parts, args.slice_method)
    n = th * tw
    if len(jax.devices()) < n:
        sys.exit(
            f"need {n} devices; have {len(jax.devices())}. Set JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} to simulate."
        )
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(th, tw), ("tile_h", "tile_w"))
    spec = P(None, "tile_h", "tile_w", None)
    h = args.halo_len

    b, s, c = args.batch_size, args.image_size, args.num_filters
    x = jnp.arange(b * s * s * c, dtype=jnp.float32).reshape(b, s, s, c)
    xs = jax.device_put(x, NamedSharding(mesh, spec))

    # -- validation vs np.pad ground truth (ref test_output, :570-584) -------
    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec, check_vma=False
    )
    def exchange_keep_halo(x):
        # Full padded tile: every tile has the same padded shape, so the
        # shard_map output tiles evenly and the validation below can check
        # the ENTIRE halo ring (all four directions + boundary fill).
        return halo_exchange(x, h, h, "tile_h", "tile_w", impl=args.impl)

    from halo_common import validate_padded_tiles

    bad = validate_padded_tiles(exchange_keep_halo(xs), x, th, tw, h, h)
    print(f"validation: {'PASSED' if bad == 0 else 'FAILED'}")
    if bad:
        sys.exit(1)

    # -- timing (exchange_keep_halo: output depends on the received halos, so
    # XLA cannot dead-code-eliminate the collectives) -------------------------
    for _ in range(args.warmup):
        out = exchange_keep_halo(xs)
    jax.block_until_ready(out)
    times = []
    for _ in range(args.iterations):
        t0 = time.perf_counter()
        out = exchange_keep_halo(xs)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) * 1e3)
    print(
        f"halo exchange[{args.impl}] {s}x{s} halo={h} {args.slice_method} x{n}: "
        f"mean {statistics.mean(times):.4f} ms  median {statistics.median(times):.4f} ms"
    )


if __name__ == "__main__":
    main()
