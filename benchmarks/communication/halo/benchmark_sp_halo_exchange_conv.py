"""Full conv+halo validation suite (kernel-shape-aware).

TPU rebuild of reference
``benchmarks/communication/halo/benchmark_sp_halo_exchange_conv.py``: the most
thorough of the reference's halo harnesses, adding

- kernel-size-aware neighbor pruning (ref ``:219-236``): a 1xk kernel needs
  halos only along W, a kx1 kernel only along H — here expressed as per-dim
  halo lengths ``((kh-1)/2, (kw-1)/2)`` passed to the same exchange (the
  "pruning" falls out: a zero halo posts no collective on that axis);
- a CPU/accelerator switch (ref ``ENABLE_GPU``) → ``--platform {auto,cpu}``;
- three validation modes (ref ``:940-1092``), each switchable:
  * ``--val-recv``  — received halo ring vs ``np.pad`` ground truth;
  * ``--val-conv``  — distributed conv output vs sequential full-image conv
    (ref ``ENABLE_VAL_CONV``);
  * ``--val-small-conv`` — run the conv ONLY on each tile's halo-extended
    boundary strips and compare against the same windows of the sequential
    output (ref ``ENABLE_VAL_SMALL_CONV``, the probe that distinguishes
    exchange bugs from conv nondeterminism).
"""

import argparse
import functools
import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..", "..")
)


def get_args():
    p = argparse.ArgumentParser(description="conv+halo validation suite (TPU-native)")
    p.add_argument("--image-size", type=int, default=256)
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--num-filters", type=int, default=8)
    p.add_argument("--in-channels", type=int, default=3)
    p.add_argument("--num-spatial-parts", type=int, default=4)
    p.add_argument("--slice-method", type=str, default="square")
    p.add_argument(
        "--kernel", type=str, default="3x3",
        help="HxW kernel, odd dims; e.g. 3x3, 1x7, 7x1, 5x5",
    )
    p.add_argument("--impl", type=str, default="xla", choices=["xla", "pallas"])
    p.add_argument(
        "--platform", type=str, default="auto", choices=["auto", "cpu"],
        help="cpu forces host execution (ref ENABLE_GPU=False)",
    )
    p.add_argument("--val-recv", action="store_true", default=True)
    p.add_argument("--no-val-recv", dest="val_recv", action="store_false")
    p.add_argument("--val-conv", action="store_true", default=True)
    p.add_argument("--no-val-conv", dest="val_conv", action="store_false")
    p.add_argument("--val-small-conv", action="store_true", default=True)
    p.add_argument("--no-val-small-conv", dest="val_small_conv", action="store_false")
    return p.parse_args()


def main():
    args = get_args()

    if args.platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update(
            "jax_num_cpu_devices", max(args.num_spatial_parts, 1)
        )
    else:
        from mpi4dl_tpu.utils import apply_platform_env

        apply_platform_env()

    import jax
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from mpi4dl_tpu.config import tile_grid
    from mpi4dl_tpu.parallel.halo import halo_exchange

    kh, kw = (int(v) for v in args.kernel.split("x"))
    if kh % 2 == 0 or kw % 2 == 0:
        sys.exit("kernel dims must be odd")
    hh, hw = (kh - 1) // 2, (kw - 1) // 2  # per-dim halo = neighbor pruning

    th, tw = tile_grid(args.num_spatial_parts, args.slice_method)
    n = th * tw
    if len(jax.devices()) < n:
        sys.exit(
            f"need {n} devices; have {len(jax.devices())}. Set JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} to simulate."
        )
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(th, tw), ("tile_h", "tile_w"))
    spec = P(None, "tile_h", "tile_w", None)

    b, s, cin, cout = (
        args.batch_size,
        args.image_size,
        args.in_channels,
        args.num_filters,
    )
    x = jnp.arange(b * s * s * cin, dtype=jnp.float32).reshape(b, s, s, cin)
    xs = jax.device_put(x, NamedSharding(mesh, spec))
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((kh, kw, cin, cout)) * 0.05, jnp.float32)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, P()),
        out_specs=(spec, spec),
        check_vma=False,
    )
    def dist(x, w):
        p = halo_exchange(x, hh, hw, "tile_h", "tile_w", impl=args.impl)
        y = lax.conv_general_dilated(p, w, (1, 1), "VALID", dimension_numbers=dn)
        # Full padded tile (tiles evenly) so --val-recv covers the whole
        # halo ring: all exchange directions and all boundary fills.
        return y, p

    @jax.jit
    def seq(x, w):
        return lax.conv_general_dilated(
            x, w, (1, 1), ((hh, hh), (hw, hw)), dimension_numbers=dn
        )

    got_y, got_pad = dist(xs, w)
    got_y, got_pad = np.asarray(got_y), np.asarray(got_pad)
    want_y = np.asarray(seq(x, w))
    t_h, t_w = s // th, s // tw
    failures = 0

    if args.val_recv:
        from halo_common import validate_padded_tiles

        bad = validate_padded_tiles(got_pad, x, th, tw, hh, hw)
        print(f"val-recv (kernel {kh}x{kw}, halo ({hh},{hw})): "
              f"{'PASSED' if bad == 0 else 'FAILED'}")
        failures += bad

    if args.val_conv:
        err = np.max(np.abs(got_y - want_y))
        ok = err <= 1e-4
        print(f"val-conv: max|err| = {err:.3e} {'PASSED' if ok else 'FAILED'}")
        failures += 0 if ok else 1

    if args.val_small_conv:
        # Conv only the boundary strips: for each interior tile edge, take the
        # sequential output rows/cols that straddle it and compare with the
        # distributed output of the tiles on each side. An exchange bug
        # corrupts exactly these windows first (ref :1038-1092).
        bad = 0
        for i in range(1, th):  # horizontal boundaries (need hh > 0)
            if hh == 0:
                break
            r0 = i * t_h - hh
            strip_want = want_y[:, r0 : r0 + 2 * hh, :, :]
            strip_got = got_y[:, r0 : r0 + 2 * hh, :, :]
            if np.max(np.abs(strip_want - strip_got)) > 1e-4:
                bad += 1
                print(f"small-conv H-boundary {i}: MISMATCH", file=sys.stderr)
        for j in range(1, tw):  # vertical boundaries (need hw > 0)
            if hw == 0:
                break
            c0 = j * t_w - hw
            strip_want = want_y[:, :, c0 : c0 + 2 * hw, :]
            strip_got = got_y[:, :, c0 : c0 + 2 * hw, :]
            if np.max(np.abs(strip_want - strip_got)) > 1e-4:
                bad += 1
                print(f"small-conv W-boundary {j}: MISMATCH", file=sys.stderr)
        print(f"val-small-conv: {'PASSED' if bad == 0 else 'FAILED'}")
        failures += bad

    if failures:
        sys.exit(1)
    print("ALL VALIDATIONS PASSED")


if __name__ == "__main__":
    main()
