"""Shared benchmark runner.

Replaces the per-script boilerplate of the reference's 8 training benchmarks
(``benchmarks/*/benchmark_*.py``): parse the shared CLI, build the
``ParallelConfig`` + trainer for the requested parallelism mode, run epochs
with per-step timing, print images/sec mean/median at exit
(ref timing: ``benchmark_amoebanet_sp.py:322-367`` — CUDA events there,
host-side timing with ``block_until_ready`` here; both wall-clock).

Every benchmark is one SPMD program over however many devices JAX sees:
one real TPU chip, a CPU simulation
(``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=N``),
or a multi-host pod — ``build_config`` joins the distributed world
(``multihost.initialize_distributed``), ``make_trainer`` builds a DCN-aware
mesh, and ``run_training`` feeds each host only its data shard. There is no
``mpirun_rsh`` contract; single-host launch needs no launcher at all.
"""

from __future__ import annotations

import os
import statistics
import sys
import time

import numpy as np


def parse_csv_ints(s):
    if s is None:
        return None
    return [int(v) for v in str(s).split(",")]


def build_config(args, spatial: bool, num_cells: int | None = None):
    import jax.numpy as jnp

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.elastic import maybe_supervise
    from mpi4dl_tpu.parallel import multihost
    from mpi4dl_tpu.utils import enable_compilation_cache

    # --max-restarts: re-exec under the fault-tolerance supervisor. Must
    # happen HERE — before make_mesh/init touch the accelerator, which a
    # supervisor process may not hold (TPU access is per-process exclusive).
    maybe_supervise(args)
    enable_compilation_cache()  # multi-minute XLA compiles amortize across runs
    # Join the multi-host world if one is configured (no-op single-process;
    # the reference's dist.init_process_group moment, comm.py:154-159).
    multihost.initialize_distributed()
    return ParallelConfig(
        batch_size=args.batch_size,
        parts=args.parts,
        split_size=args.split_size,
        num_spatial_parts=tuple(parse_csv_ints(args.num_spatial_parts) or (4,)),
        spatial_size=args.spatial_size if spatial else 0,
        slice_method=args.slice_method,
        times=args.times,
        image_size=args.image_size,
        num_classes=args.num_classes,
        balance=parse_csv_ints(args.balance),
        halo_d2=args.halo_d2,
        fused_layers=args.fused_layers,
        local_dp=args.local_DP,
        precision=args.precision,
    )


def build_resnet(args, cfg, spatial_cells=0):
    """Returns (cells, plain_twin[, n_spatial_override]).

    --halo-D2 swaps the spatial region for the fused-halo design (one wide
    exchange per ``--fused-layers`` bottleneck cells)."""
    import jax.numpy as jnp

    from mpi4dl_tpu.models.resnet import get_resnet_v2, get_resnet_v2_d2
    from mpi4dl_tpu.utils import get_depth

    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    # The reference resnet benchmarks hardcode resnet_n=12 (ResNet-110,
    # e.g. benchmark_resnet_lp.py:92-94); MPI4DL_TPU_RESNET_N overrides the
    # same constant here so smoke tests/CI can drive the full script
    # plumbing without paying a 54-cell compile.
    depth = get_depth(2, int(os.environ.get("MPI4DL_TPU_RESNET_N", "12")))
    kw = dict(
        depth=depth,
        num_classes=args.num_classes,
        # Final feature map is image/4; pool it fully (1x1 output).
        pool_kernel=max(args.image_size // 4, 1),
    )
    if args.halo_d2 and spatial_cells:
        cells, plain, n_sp = get_resnet_v2_d2(
            spatial_cells=spatial_cells,
            fused_layers=args.fused_layers,
            dtype=dtype,
            **kw,
        )
        return cells, plain, n_sp
    return (
        get_resnet_v2(spatial_cells=spatial_cells, dtype=dtype, **kw),
        get_resnet_v2(dtype=jnp.float32, **kw),
    )


def build_amoebanet(args, cfg, spatial_cells=0):
    import jax.numpy as jnp

    from mpi4dl_tpu.models.amoebanet import amoebanetd

    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    kw = dict(
        num_classes=args.num_classes,
        num_layers=args.num_layers,
        num_filters=args.num_filters,
    )
    return (
        amoebanetd(
            spatial_cells=spatial_cells,
            halo_d2=args.halo_d2 and spatial_cells > 0,
            dtype=dtype,
            **kw,
        ),
        amoebanetd(dtype=jnp.float32, **kw),
    )


def make_trainer(args, cfg, cells, plain_cells, gems: bool = False, n_spatial=None):
    import jax

    from mpi4dl_tpu.parallel import multihost
    from mpi4dl_tpu.parallel.pipeline import GemsMasterTrainer, PipelineTrainer
    from mpi4dl_tpu.train import Trainer

    n_dev = cfg.num_devices
    if len(jax.devices()) < n_dev:
        sys.exit(
            f"config needs {n_dev} devices (mesh {cfg.mesh_shape}); "
            f"have {len(jax.devices())}. For CPU simulation set "
            f"JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count={n_dev}"
        )
    # DCN-aware placement on multi-slice systems; identical to
    # cfg.make_mesh() on one slice (multihost.make_multihost_mesh docs).
    mesh = multihost.make_multihost_mesh(cfg)
    override = n_spatial  # None → trainers derive from config stage bounds
    if n_spatial is None:
        n_spatial = (
            PipelineTrainer.spatial_cell_count(len(cells), cfg)
            if cfg.spatial_size
            else 0
        )
    if gems:
        if getattr(args, "enable_master_comm_opt", False):
            # Accepted for CLI parity (ref --enable-master-comm-opt,
            # train_spatial_master.py:229-455). The optimization it selects
            # there — pairwise flat param/grad P2P instead of ordered
            # allreduces — is the DEFAULT and only path here: the mirror
            # direction's params arrive by one pipe-axis ppermute and its
            # AD transpose is the paired grad reduce. Nothing to switch.
            print(
                "note: --enable-master-comm-opt is implied on TPU "
                "(mirror ppermute == the comm-opt pairwise exchange)"
            )
        return (
            GemsMasterTrainer(
                cells, cfg, plain_cells=plain_cells, num_spatial_cells=override,
                mesh=mesh,
            ),
            n_spatial,
        )
    if cfg.split_size == 1 or cfg.spatial_size == cfg.split_size:
        return (
            Trainer(
                cells,
                num_spatial_cells=n_spatial,
                config=cfg,
                plain_cells=plain_cells,
                mesh=mesh,
            ),
            n_spatial,
        )
    return (
        PipelineTrainer(
            cells, cfg, plain_cells=plain_cells, num_spatial_cells=override,
            mesh=mesh,
        ),
        n_spatial,
    )


def run_training(args, trainer, tag: str):
    """Epoch loop with per-step wall-clock timing (ref
    ``benchmark_amoebanet_sp.py:315-367``), optional checkpoint/resume and
    ``jax.profiler`` tracing (TPU-native additions)."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu import checkpoint as ckpt
    from mpi4dl_tpu.data import get_dataset
    from mpi4dl_tpu.profiling import trace

    cfg = trainer.config
    chunks = getattr(trainer, "chunks", 1)
    global_batch = chunks * cfg.batch_size
    # Multi-process: every host loads ONLY its share of the global batch
    # (the data axis may span hosts; shard_batch assembles the global array
    # via make_array_from_process_local_data — multihost.put_global). The
    # reference instead loads the global batch on every rank and slices
    # (benchmark_amoebanet_sp.py:329-340).
    if jax.process_count() > 1:
        from mpi4dl_tpu.parallel.multihost import data_shard, local_batch_size

        host_batch = local_batch_size(trainer.mesh, global_batch)
        shard_id, num_shards = data_shard(trainer.mesh)
    else:
        host_batch, shard_id, num_shards = global_batch, 0, 1
    ds = get_dataset(
        args, host_batch, cfg.num_classes, shard_id=shard_id, num_shards=num_shards
    )

    if hasattr(trainer, "init_params") or not hasattr(trainer, "n_spatial"):
        state = trainer.init(jax.random.PRNGKey(0))
    else:
        state = trainer.init(
            jax.random.PRNGKey(0),
            (global_batch, cfg.image_size, cfg.image_size, 3),
        )
    ckpt_dir = getattr(args, "checkpoint_dir", None)
    if ckpt_dir and getattr(args, "resume", False):
        try:
            state = ckpt.restore_checkpoint(ckpt_dir, state)
            print(f"resumed from step {int(state.step)}")
        except FileNotFoundError:
            pass

    from mpi4dl_tpu import elastic

    hb = elastic.heartbeat_path_from_env()  # supervised run (--max-restarts)
    # Test-only chaos knob: crash/hang the process once it reaches step N
    # on a fresh (non-resumed) run — exercises the supervisor's two failure
    # detectors end-to-end (tests/test_elastic.py).
    crash_at = int(os.environ.get("MPI4DL_TPU_CRASH_AT_STEP", "-1"))
    hang_at = int(os.environ.get("MPI4DL_TPU_HANG_AT_STEP", "-1"))

    # Resume honors the restored state.step as work ALREADY DONE: earlier
    # (epoch, step) slots are skipped — consuming their batches, so the
    # resumed run replays the identical data order — instead of re-running
    # the full step budget on top of the checkpointed weights (which would
    # train up to (max_restarts+1)x the requested duration under repeated
    # crashes).
    done = int(state.step)
    seen = 0  # global (epoch, step) slots consumed, trained or skipped
    trained = 0
    perf = []
    with trace(getattr(args, "trace_dir", None)):
        for epoch in range(args.num_epochs):
            for step, (x, y) in enumerate(ds):
                max_steps = getattr(args, "max_steps", None)
                if max_steps is not None and step >= max_steps:
                    break
                seen += 1
                if seen <= done:
                    # The fast-forward replay is progress too: with a slow
                    # data loader a long skip phase must not read as a
                    # wedge to the supervisor.
                    if hb:
                        elastic.touch(hb)
                    continue
                if not getattr(args, "resume", False):
                    if int(state.step) == crash_at:
                        os._exit(3)
                    if int(state.step) == hang_at:
                        time.sleep(3600)
                xs, ys = trainer.shard_batch(jnp.asarray(x), jnp.asarray(y))
                t0 = time.perf_counter()
                state, metrics = trainer.train_step(state, xs, ys)
                loss = float(metrics["loss"])  # blocks
                dt = time.perf_counter() - t0
                if hb:
                    elastic.touch(hb)
                trained += 1
                if trained > 1:  # skip compile step, like the ref's warmup
                    perf.append(global_batch / dt)
                if args.verbose:
                    print(
                        f"epoch {epoch} step {step}: loss {loss:.4f} "
                        f"acc {float(metrics['accuracy']):.4f} "
                        f"({global_batch / dt:.3f} img/s)"
                    )
                if ckpt_dir and int(state.step) % args.checkpoint_every == 0:
                    ckpt.save_checkpoint(ckpt_dir, state)
    if hb:
        elastic.touch(hb)  # post-loop phases below must not read as a wedge
    if ckpt_dir:
        ckpt.save_checkpoint(ckpt_dir, state)
    if perf:
        mean_ips = statistics.mean(perf)
        line = (
            f"{tag}: Mean {mean_ips:.3f} img/s "
            f"Median {statistics.median(perf):.3f} img/s"
        )
        # MFU against the model's analytic FLOPs (BASELINE.json north star
        # is stated in MFU; the reference never reports it). Counted on the
        # plain twin — same math, no spatial collectives to trace.
        try:
            from mpi4dl_tpu.flops import mfu, train_flops_per_image

            fpi = train_flops_per_image(trainer.plain_cells, cfg.image_size)
            util = mfu(mean_ips, fpi, n_devices=jax.device_count())
            if util is not None:
                line += f" MFU {100 * util:.1f}%"
        except Exception as e:  # never let accounting kill a benchmark
            line += f" (MFU unavailable: {e})"
        print(line)
    if getattr(args, "eval_batches", 0):
        # skip: the (epoch, step) slots training consumed, reduced modulo
        # the dataset's per-epoch length — `seen` accumulates across epochs
        # and resume fast-forwards, and skipping whole dataset revolutions
        # would just wrap the stream back to the same position after
        # pointless "dataset exhausted" warnings (ADVICE r3). The eval
        # stream starts past the trained prefix instead of presenting
        # train-set batches as "evaluation".
        try:
            per_epoch = len(ds)
        except TypeError:
            per_epoch = 0
        run_eval(
            args, trainer, state, ds, args.eval_batches,
            skip=seen % per_epoch if per_epoch else seen,
        )
    return state


def run_eval(args, trainer, state, ds, n: int, skip: int = 0):
    """BN-calibrate on ``n`` batches, evaluate on ``n`` more
    (mpi4dl_tpu/evaluate.py; the reference never evaluates).

    Spatial ``Trainer`` configs evaluate through the trainer's own sharded
    forward (``spatial_collect_batch_stats``/``spatial_evaluate``) — at the
    resolutions this framework targets the full-image plain twin cannot run
    on one device. Pipeline/GEMS configs evaluate on the plain twin with
    the trained params unstacked to the flat cell list (their stage-sharded
    forward exists for training; eval at their scale re-hosts the params).

    The first ``skip`` batches of the stream (the ones training consumed)
    are passed over so calibration/test data is fresh; if the dataset is
    too short the stream wraps with a warning (eval then overlaps train
    data — small datasets have nothing else to offer)."""
    import jax.numpy as jnp

    from mpi4dl_tpu import elastic
    from mpi4dl_tpu.evaluate import collect_batch_stats, evaluate

    hb = elastic.heartbeat_path_from_env()
    cells = trainer.plain_cells
    params = state.params
    if hasattr(trainer, "unstack_params"):
        params = trainer.unstack_params(params)
    spatial = (
        not hasattr(trainer, "unstack_params")
        and getattr(trainer, "n_spatial", 0) > 0
    )

    it = iter(ds)

    def take():
        nonlocal it
        try:
            b = next(it)
        except StopIteration:
            print(
                "eval: dataset exhausted — wrapping (eval batches overlap "
                "training data)",
                flush=True,
            )
            it = iter(ds)
            try:
                b = next(it)
            except StopIteration:
                raise ValueError("eval: dataset is empty") from None
        if hb:
            elastic.touch(hb)
        return b

    for _ in range(skip):
        take()
    cal = [jnp.asarray(take()[0]) for _ in range(n)]
    test = [
        (jnp.asarray(x), jnp.asarray(y)) for x, y in (take() for _ in range(n))
    ]
    if spatial:
        from mpi4dl_tpu.evaluate import (
            spatial_collect_batch_stats,
            spatial_evaluate,
        )

        stats = spatial_collect_batch_stats(trainer, params, cal)
        if hb:
            elastic.touch(hb)
        res = spatial_evaluate(trainer, params, stats, test)
    else:
        stats = collect_batch_stats(cells, params, cal)
        if hb:
            elastic.touch(hb)
        res = evaluate(cells, params, stats, test)
    print(
        f"eval ({n} cal / {n} test batches, {res['count']} images): "
        f"loss {res['loss']:.4f} acc {res['accuracy']:.4f}"
    )
    return res
