"""AmoebaNet-D SP+GEMS(+PP) benchmark

TPU rebuild of reference ``benchmarks/gems_master_with_spatial_parallelism/benchmark_amoebanet_gems_master_with_sp.py``: same CLI flags
(``torchgems/parser.py:21-143``), same model and parallelism mode, one SPMD
process over the JAX device mesh instead of ``mpirun_rsh`` ranks.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from common import (
    build_amoebanet,
    build_config,
    build_resnet,
    make_trainer,
    run_training,
)

from mpi4dl_tpu.parser import get_parser


def main():
    from mpi4dl_tpu.utils import apply_platform_env

    apply_platform_env()
    args = get_parser().parse_args()
    cfg = build_config(args, spatial=True)
    n_cells = len(build_amoebanet(args, cfg)[1])
    from mpi4dl_tpu.parallel.pipeline import PipelineTrainer

    n_spatial = (
        PipelineTrainer.spatial_cell_count(n_cells, cfg) if cfg.spatial_size else 0
    )
    built = build_amoebanet(args, cfg, spatial_cells=n_spatial)
    n_override = built[2] if len(built) == 3 else None
    cells, plain = built[0], built[1]
    trainer, _ = make_trainer(
        args, cfg, cells, plain, n_spatial=n_override, gems=True
    )
    run_training(args, trainer, tag="benchmark_amoebanet_gems_master_with_sp")


if __name__ == "__main__":
    main()
