"""Serving load-generator benchmark — thin entry over
``python -m mpi4dl_tpu.serve`` (the implementation lives in
:mod:`mpi4dl_tpu.serve.loadgen` so tests and bench.py import it as a
library; this script exists so serving benchmarks live next to the
training ones).

Examples::

    # closed loop on the CPU backend, synthetic calibrated ResNet
    JAX_PLATFORMS=cpu python benchmarks/serving/loadgen.py \
        --requests 128 --concurrency 32 --max-batch 8

    # open loop at a fixed offered rate against a real checkpoint
    python benchmarks/serving/loadgen.py --ckpt /ckpts/run1 \
        --mode open --rate 200 --duration 10 --deadline-ms 50 --lint
"""

import sys

from mpi4dl_tpu.serve.__main__ import main

if __name__ == "__main__":
    sys.exit(main())
