"""Headline benchmark: ResNet-110(v2) training throughput at 1024x1024.

Reference baseline (BASELINE.md): best published MPI4DL number for ResNet at
1024px is ~3.1 images/sec (batch 2, spatial parallelism, square slicing +
halo-D2, multi-GPU MVAPICH2-GDR cluster; read off
``docs/assets/images/ResNet_img_size_1024.png``). This script trains the same
depth-110 v2 model at 1024px on however many devices are available (one real
TPU chip under the driver) and prints one JSON line:

    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_IMAGES_PER_SEC = 3.1  # ResNet 1024px bs=2, best SP config (BASELINE.md)


def main():
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.train import Trainer
    from mpi4dl_tpu.utils import get_depth

    platform = jax.devices()[0].platform
    image_size = int(os.environ.get("BENCH_IMAGE_SIZE", "1024"))
    batch = int(os.environ.get("BENCH_BATCH", "2"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    warmup = 2
    if platform == "cpu" and "BENCH_IMAGE_SIZE" not in os.environ:
        image_size, steps = 128, 3  # keep the CPU smoke path tractable

    depth = get_depth(2, 12)  # 110 — the reference benchmark's ResNet
    dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    cells = get_resnet_v2(
        depth=depth, num_classes=10, pool_kernel=image_size // 4, dtype=dtype
    )

    cfg = ParallelConfig(
        batch_size=batch, split_size=1, spatial_size=0, image_size=image_size
    )
    # "scan" remat: ResNet-110 @1024px stores ~64G of activations with no
    # remat — far beyond one chip's HBM — and the scan policy (one compiled
    # body per repeated stage, compact un-padded residuals, scheduling
    # barriers) trains 2.4x faster than per-cell jax.checkpoint on top of
    # fitting (see Trainer.__init__ docstring for measurements).
    # "scan_save" additionally keeps conv outputs (~2 bytes/pixel-channel)
    # to skip the backward's forward-recompute; it fits up to ~2M pixels
    # per example on one chip — try it first, fall back to "scan" on OOM.
    remat_pref = os.environ.get("BENCH_REMAT")
    remats = [remat_pref] if remat_pref else ["scan_save", "scan"]

    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((batch, image_size, image_size, 3)), dtype
    )
    y = jnp.asarray(rng.integers(0, 10, size=(batch,)), jnp.int32)

    state = trainer = None
    for remat in remats:
        try:
            trainer = Trainer(cells, num_spatial_cells=0, config=cfg, remat=remat)
            xs, ys = trainer.shard_batch(x, y)
            state = trainer.init(jax.random.PRNGKey(0), x.shape, dtype=dtype)
            for _ in range(warmup):
                state, metrics = trainer.train_step(state, xs, ys)
            # A device-to-host READ (not just block_until_ready) is the only
            # portable way to force the dispatched chain to fully execute on
            # every backend — tunneled/virtualized TPU runtimes have been
            # observed to report readiness without having run dependent
            # steps, inflating throughput ~400x. The final loss value
            # transitively depends on every step in the chain, so one scalar
            # read times the real work.
            float(metrics["loss"])
            break
        except jax.errors.JaxRuntimeError as e:  # OOM → leaner policy
            # Only genuine memory exhaustion justifies retrying with a
            # leaner remat policy; anything else (e.g. a kernel compile
            # failure) must surface immediately, not after a doubled
            # time-to-failure (ADVICE.md round-1 low finding).
            msg = str(e)
            is_oom = "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
            if not is_oom or remat == remats[-1]:
                raise
            print(f"# remat={remat} OOM; retrying leaner", flush=True)
            state = trainer = None

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.train_step(state, xs, ys)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    images_per_sec = batch * steps / dt
    print(
        json.dumps(
            {
                "metric": f"resnet110_{image_size}px_bs{batch}_train_{platform}",
                "value": round(images_per_sec, 3),
                "unit": "images/sec",
                "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 3),
                "remat": trainer.remat,
            }
        )
    )


if __name__ == "__main__":
    main()
