"""Headline benchmark: training throughput vs the reference's published numbers.

Headline metric (the JSON ``value``): AmoebaNet-D (18 layers / 416 filters,
the reference benchmark defaults — its headline model; BASELINE.json configs
are AmoebaNet-centric) @1024px bs=2, vs the reference's best published
AmoebaNet@1024 number ~3.0 img/s (multi-GPU MVAPICH2-GDR cluster; read off
``docs/assets/images/AmeobaNet_img_size_1024.png`` — BASELINE.md).
``BENCH_MODEL=resnet`` switches the headline to ResNet-110(v2) @1024 bs2
(ref best ~3.1, ``ResNet_img_size_1024.png``).

``extras`` carries the other published chart points:

- ResNet 1024px bs=2: ref best ≈3.1 img/s (ResNet_img_size_1024.png)
- ResNet 2048px bs=1: ref best ≈1.0 img/s (ResNet_img_size_2048.png)
- AmoebaNet 2048px bs=2: ref best ≈5.1 img/s (AmeobaNet_img_size_2048.png)
- AmoebaNet 2048px bs=1: ref best ≈2.9 img/s (same chart)

Every entry also reports MFU (model-FLOPs utilization, analytic conv+dot
count — see mpi4dl_tpu/flops.py); the north star is ≥45% (BASELINE.json).
Train entries carry p50/p90/p99 step-time tails (``step_time_s``), and a
``serving_*`` extra measures the online serving engine (mpi4dl_tpu/serve):
dynamic micro-batching throughput vs the batch-size-1 serial baseline with
request-latency percentiles (``BENCH_SERVING=0`` disables). The
``sp2x2_overlap`` extra runs the spatial-parallel train step's
monolithic-vs-decomposed conv A/B on a CPU-mesh subprocess and embeds both
arms' measured ``trace_overlap_ratio`` (``BENCH_SP_OVERLAP=0`` disables);
``serving_sharded`` runs the same A/B on the serving hot path — a
2×2-sharded engine under closed-loop load per arm, ratio + per-request
p99 per arm (``BENCH_SERVING_SHARDED=0`` disables); ``pipeline`` runs the
LP pipeline's schedule A/B — gpipe vs interleaved 1f1b — embedding both
arms' measured bubble fraction + img/s (``BENCH_PIPELINE=0`` disables);
``tiled_gigapixel`` walks the largest image ONE chip serves through the
halo-correct tile stream (serve/tiled.py) and measures fixed-size request
latency + the tile/stitch split (``BENCH_TILED=0`` disables;
``BENCH_TILED_PX``/``BENCH_TILED_TILE``/``BENCH_TILED_WALK`` scale it);
``numerics`` measures the canary sentinel's ON/OFF rps tax and times a
live bit-flip corrupt drill's corruption→fence detection latency
(``BENCH_NUMERICS=0`` disables); ``incident`` reruns the kill drill under
the incident engine and scores it — MTTD (page→open), MTTR (open→close),
and whether the auto-postmortem blames the injected chaos op
(``BENCH_INCIDENT=0`` disables).

Output protocol (timeout-proof by design): a full JSON result line is
printed AND FLUSHED the moment the headline measurement lands, and an
updated full line (a superset: same headline + one more extra) after each
extra completes.  Every printed line is a complete, valid result — a driver
that keeps either the first or the last JSON line gets a usable record even
if this process is killed mid-extra.  SIGTERM/SIGINT re-emit the latest
result before exiting.  All extras run under a wall-clock budget
(``BENCH_TIME_BUDGET`` seconds, default 1800): an extra is skipped — with a
"skipped" marker — rather than started if the budget is exhausted.

Line shape:
    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N,
     "mfu": ..., "extras": {...}}
If NOTHING produced a throughput the single line carries an explicit
top-level "error" and the process exits nonzero (a null value must never
masquerade as a measurement).
"""

from __future__ import annotations

import functools
import json
import os
import signal
import sys
import tempfile
import time

import numpy as np

RESNET_BASELINE = 3.1  # img/s, ResNet@1024 bs2, best SP config (BASELINE.md)
RESNET_2048_BASELINE = 1.0  # img/s bs=1 (bs=2 OOMs every published scheme)
AMOEBA_BASELINE = {  # img/s (BASELINE.md chart reads)
    (1024, 2): 3.0,
    (2048, 2): 5.1,
    (2048, 1): 2.9,
}

_T0 = time.monotonic()
_RESULT: dict = {}  # latest complete result; emitted incrementally
_LAST_RUN: dict = {}  # trainer/state/batch of the last successful measurement
_REGISTRY = None  # telemetry.MetricsRegistry, created in main()
_TELEMETRY_LOG = None  # telemetry.JsonlWriter (MPI4DL_TPU_TELEMETRY_DIR)


@functools.lru_cache(maxsize=1)
def _git_rev() -> str:
    """HEAD revision (keys the known-fatal sentinel: a cached failure
    verdict is only trusted while the code that produced it is unchanged).
    "unknown" — e.g. no git — never equals a stored rev, so it fails open
    (retry) rather than hiding a fix behind a stale verdict."""
    import subprocess

    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10, check=True,
        ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001
        return "unknown"


def _exception_chain_text(e) -> str:
    """str(e) plus every chained __cause__/__context__ message: a
    transport flake wrapped in an exception whose own message lacks the
    signature must still classify as transient (ADVICE r4). Both branches
    are walked — a node with an explicit __cause__ can still carry the
    flake in its __context__ (raise ... from other inside an except)."""
    parts, seen, todo = [], set(), [e]
    while todo:
        exc = todo.pop()
        if exc is None or id(exc) in seen:
            continue
        seen.add(id(exc))
        parts.append(str(exc))
        todo.extend((exc.__cause__, exc.__context__))
    return "\n".join(parts)


def _is_transient_failure(exc_or_msg) -> bool:
    """Transport/infrastructure flakes from the tunneled compile helper —
    failures that say nothing about whether the PROGRAM can compile, so
    they must never produce a "confirmed" known-fatal verdict. The
    signatures are from observed incidents on this runtime; a genuine
    compile failure surfaces as ``tpu_compile_helper subprocess exit
    code 1`` (HBM OOM, Mosaic rejection...) and is NOT in this list.

    Accepts an exception (scans the whole __cause__/__context__ chain)
    or a plain string."""
    msg = (
        exc_or_msg
        if isinstance(exc_or_msg, str)
        else _exception_chain_text(exc_or_msg)
    )
    needles = (
        "response body closed",
        "read body:",
        "Connection reset",
        "Broken pipe",
        "Remote end closed",
        "EOF occurred",
        "Temporary failure",
        # NOT "timed out": a compile-helper deadline on a too-large
        # program is deterministic — classifying it transient would buy
        # a doomed ~10-min retry and a mislabeled skip message.
    )
    return any(n in msg for n in needles)


def sentinel_skip_reason(
    ent, now_rev: str, remaining_s: float, force_retry: bool
) -> "str | None":
    """Decide whether a known-fatal sentinel entry should skip the attempt.

    Returns a reason string to skip, or None to (re)run. Rules (VERDICT r3
    weak #6 + ADVICE r3 medium):

    - ``force_retry`` (BENCH_RETRY_FATAL=1) always reruns;
    - legacy string entries (pre-revision-keying) rerun — the code has
      certainly changed since they were written;
    - entries from a different (or unknowable) git revision rerun — a code
      change invalidates the verdict, so a fix can't be hidden by a stale
      cache;
    - "confirmed" entries at the current revision skip (the attempt
      genuinely raised, and nothing has changed);
    - "provisional" entries (attempt started, never concluded — a driver
      kill mid-compile) rerun ONCE when the budget still allows a full
      attempt including a possible fatal compile (~600 s); a second
      provisional marker at the same revision (``tries >= 2``) skips —
      a compile that outlives the driver's kill window twice would
      otherwise burn the tail of every future run (the repeated-doomed-
      compile loop the pre-mark exists to prevent). With a thinner budget
      they also skip, since starting a doomed compile would only re-create
      the same provisional marker.
    """
    if force_retry:
        return None
    if not isinstance(ent, dict):
        return None
    if ent.get("rev") != now_rev or now_rev == "unknown":
        return None
    if ent.get("status") == "confirmed":
        return (
            f"known-fatal (cached @{str(ent.get('rev', '?'))[:8]}): "
            + str(ent.get("msg", ""))[:80]
        )
    if int(ent.get("tries", 1)) >= 2:
        how = (
            "failed transiently"
            if str(ent.get("msg", "")).startswith("transient: ")
            else "never concluded"
        )
        return (
            f"provisional marker retried and {how} twice at this "
            "revision — treating as fatal (BENCH_RETRY_FATAL=1 overrides)"
        )
    if remaining_s >= 600:
        return None
    return (
        "provisional marker (prior attempt never concluded); "
        "budget too thin to retry"
    )


def _emit():
    """Print the current result as one flushed JSON line (see module doc).
    Each line carries a ``telemetry`` snapshot in the JSONL metrics-event
    schema (mpi4dl_tpu.telemetry.jsonl), so BENCH_*.json records and the
    MPI4DL_TPU_TELEMETRY_DIR event log stay one schema."""
    if not _RESULT:
        return
    if _REGISTRY is not None and _REGISTRY.names():
        from mpi4dl_tpu import telemetry

        ev = telemetry.metrics_event(_REGISTRY)
        _RESULT["telemetry"] = ev
        if _TELEMETRY_LOG is not None:
            _TELEMETRY_LOG.write(ev)
    print(json.dumps(_RESULT), flush=True)


def _on_signal(signum, frame):  # noqa: ARG001
    # Re-emit what we have and exit hard: XLA teardown can hang, and the
    # driver only needs the stdout line.  Exit 0 only if a real value landed.
    if _RESULT.get("value") is not None:
        _RESULT.setdefault("note", f"interrupted by signal {signum}")
        _emit()
        os._exit(0)
    out = {
        "metric": "bench_interrupted",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "error": f"signal {signum} before any successful measurement",
    }
    for key in ("extras", "headline_error"):
        if _RESULT.get(key):
            out[key] = _RESULT[key]
    print(json.dumps(out), flush=True)
    os._exit(1)


def _budget() -> float:
    return float(os.environ.get("BENCH_TIME_BUDGET", "1800"))


def _remaining() -> float:
    return _budget() - (time.monotonic() - _T0)


def _train_throughput(
    cells, image_size, batch, steps, warmup, dtype, remats, grad_accum=1
):
    """img/s for a Trainer over the cell list; tries remat policies in
    order, falling back on genuine OOM only (VERDICT weak #1 lesson).
    grad_accum>1 runs the batch as scanned chunks (Trainer._accum_grads) —
    the full published batch size with a chunk-sized program/working set."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.train import Trainer

    cfg = ParallelConfig(
        batch_size=batch, split_size=1, spatial_size=0, image_size=image_size
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((batch, image_size, image_size, 3)), dtype
    )
    y = jnp.asarray(rng.integers(0, 10, size=(batch,)), jnp.int32)

    from mpi4dl_tpu.profiling import StepTimer

    state = trainer = None
    for remat in remats:
        try:
            trainer = Trainer(
                cells, num_spatial_cells=0, config=cfg, remat=remat,
                grad_accum=grad_accum,
            )
            xs, ys = trainer.shard_batch(x, y)
            state = trainer.init(jax.random.PRNGKey(0), x.shape, dtype=dtype)
            for _ in range(warmup):
                state, metrics = trainer.train_step(state, xs, ys)
            # A device-to-host READ (not just block_until_ready) is the only
            # portable way to force the dispatched chain to fully execute on
            # every backend — tunneled/virtualized TPU runtimes have been
            # observed to report readiness without having run dependent
            # steps, inflating throughput ~400x. The final loss value
            # transitively depends on every step in the chain, so one scalar
            # read times the real work.
            float(metrics["loss"])
            break
        except jax.errors.JaxRuntimeError as e:
            # Retry with a leaner remat policy only for failures a smaller
            # program can actually cure — genuine memory exhaustion, or the
            # tunneled runtime's remote-compile helper dying on a too-big
            # program (measured: ResNet@2048 cell_save kills the helper with
            # an INTERNAL/HTTP-500, while the scan policies compile). Any
            # other error must surface immediately, not after a doubled
            # time-to-failure (ADVICE.md round-1 low finding).
            msg = str(e)
            retryable = (
                "RESOURCE_EXHAUSTED" in msg
                or "Out of memory" in msg
                or "tpu_compile_helper" in msg
                or "remote_compile" in msg
            )
            if not retryable or remat == remats[-1]:
                raise
            print(f"# remat={remat} failed ({msg[:80]!r}); retrying leaner", flush=True)
            state = trainer = None

    # Per-step timing (StepTimer): each step ends on the same forced
    # device READ as the warm-up (the readiness-without-execution guard
    # above), so the recorded times carry real per-step boundaries and the
    # summary's p50/p90/p99 are genuine step-latency tails — the statistic
    # the serving work needs result lines to carry. The per-step scalar
    # read costs one D2H round trip per multi-second step (<1% here) and
    # only tightens the measurement: dispatch pipelining can no longer
    # smear one slow step across its neighbors.
    timer = StepTimer(batch_size=batch, warmup=0, registry=_REGISTRY)
    for _ in range(steps):
        with timer.step():
            state, metrics = trainer.train_step(state, xs, ys)
            float(metrics["loss"])
    dt = sum(timer.times)
    if _REGISTRY is not None:
        trainer.publish_telemetry(_REGISTRY)
    # Stash the measured program for the post-headline static analysis
    # (mpi4dl_tpu.analysis): re-lowering it is a warm-cache no-op.
    _LAST_RUN.update(trainer=trainer, state=state, xs=xs, ys=ys)
    return batch * steps / dt, trainer.remat, timer.summary()


def _step_percentiles(steps_summary: dict) -> dict:
    """p50/p90/p99 step-time tails from a StepTimer summary — serving-grade
    tail statistics in every train result line, not just means."""
    return {
        p: round(steps_summary[f"step_time_{p}_s"], 4)
        for p in ("p50", "p90", "p99")
        if f"step_time_{p}_s" in steps_summary
    }


def _measure_serving() -> dict:
    """Online-serving extra: dynamic micro-batching throughput vs the
    batch-size-1 serial baseline (mpi4dl_tpu/serve, docs/SERVING.md) on a
    small calibrated AmoebaNet — many small ops per cell, the op-overhead-
    bound shape the per-call dispatch floor (~23 ms on the TPU runtime,
    PERF.md) penalizes hardest, i.e. where batching IS the serving story.
    The result line carries the tail percentiles serving is judged by."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.amoebanet import amoebanetd
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve import ServingEngine
    from mpi4dl_tpu.serve.loadgen import run_closed_loop, serial_throughput

    size = 32
    cells = amoebanetd(num_classes=10, num_layers=3, num_filters=16)
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )
    from mpi4dl_tpu.telemetry import SLOConfig

    engine = ServingEngine(
        cells, params, stats, example_shape=(size, size, 3),
        buckets=(1, 32), max_wait_s=0.003, max_queue=512,
        default_deadline_s=30.0, registry=_REGISTRY,
        # SLO evaluation on so every serving result line carries a
        # verdict (docs/OBSERVABILITY.md "SLOs & alerting"); interval
        # shortened because the whole load run lasts ~a second. A tight
        # availability objective with a loose latency threshold: the CPU
        # bench must flag dropped/rejected requests, not page on a slow
        # shared box.
        slo=SLOConfig(
            availability=0.999, latency_threshold_s=2.5,
            latency_target=0.99, interval_s=0.25,
        ),
    )
    serial = serial_throughput(engine, 32)
    attribute = os.environ.get("BENCH_ATTRIBUTION", "1") != "0"
    trace_dir = (
        tempfile.mkdtemp(prefix="mpi4dl-bench-serve-trace-")
        if attribute else None
    )
    engine.start()
    try:
        from contextlib import nullcontext

        from mpi4dl_tpu.profiling import trace as profiler_trace

        with profiler_trace(trace_dir) if attribute else nullcontext():
            rep = run_closed_loop(
                engine, 384, concurrency=96, deadline_s=30.0
            )
    finally:
        engine.stop()
    lint = engine.lint_report()
    attribution = _serving_attribution(trace_dir, lint) if attribute else None
    entry = {
        "value": round(rep["throughput_rps"], 1),
        "serial_bs1_rps": round(serial["throughput_rps"], 1),
        "speedup_vs_serial": round(
            rep["throughput_rps"] / serial["throughput_rps"], 2
        ),
        "latency_ms": {
            k: round(v * 1e3, 2)
            for k, v in rep["latency_s"].items()
            if v is not None
        },
        "mean_batch_size": round(rep["engine"]["mean_batch_size"], 1),
        "deadline_misses": rep["deadline_misses"],
        "rejected": rep["rejected_queue_full"],
        "lint_ok": lint.ok,
        "slo": engine.slo.verdict(),
        # Footprint ledger (docs/OBSERVABILITY.md "Memory"): each warmed
        # bucket's compile-time predicted peak, so BENCH_*.json records
        # the serving memory trajectory next to the throughput one
        # (bench-history trends it with an inverted regression sign).
        "peak_hbm_bytes_by_bucket": {
            str(b): e["peak_bytes"]
            for b in engine.buckets
            for e in [engine.memory_ledger.get("serve_predict", bucket=b)]
            if e is not None and e.get("peak_bytes") is not None
        },
    }
    # Phase mix + client-hop cost (docs/OBSERVABILITY.md "Federation &
    # distributed tracing"): the per-round trajectory of WHERE served
    # latency goes, next to the throughput it costs.
    if rep.get("client_overhead_s"):
        entry["client_overhead_ms"] = {
            k: round(v * 1e3, 3) for k, v in rep["client_overhead_s"].items()
        }
    # Tail forensics (docs/OBSERVABILITY.md "Tail forensics"): the
    # p99/p50 latency ratio — the tail's SHAPE, independent of the
    # box's absolute speed — trended by bench-history with the
    # regression sign inverted (a growing tail fails CI), plus how many
    # tail.samples the watcher captured this round.
    lat_p = rep.get("latency_s") or {}
    if lat_p.get("p50") and lat_p.get("p99"):
        entry["tail"] = {
            "p99_p50_ratio": round(lat_p["p99"] / lat_p["p50"], 3),
            "samples": engine.tail.captured,
            "threshold_ms": round(engine.tail.threshold() * 1e3, 3),
        }
    shares = engine.registry.get("serve_phase_share")
    if shares is not None:
        entry["phase_shares"] = {
            s["labels"]["phase"]: round(s["value"], 4)
            for s in shares.snapshot_series()
        }
    if attribution is not None:
        entry["attribution"] = attribution
    if not lint.ok:
        entry["lint_findings"] = [
            f for f in lint.findings if f["severity"] == "error"
        ]
    # Scheduler A/B (docs/SERVING.md "Scheduling"): the continuous EDF
    # scheduler vs the PR-2 FIFO windowed former, interleaved, under a
    # fixed mixed tight/bulk class load on the same model/config — the
    # per-arm tight-class p99 (and aggregate rps) land in the result
    # line so bench-history trends the EDF tail claim round over round
    # (growing tight p99 fails CI; BENCH_SCHED_AB=0 disables).
    if os.environ.get("BENCH_SCHED_AB", "1") != "0":
        entry["sched_ab"] = _measure_sched_ab(cells, params, stats)
    return entry


def _measure_sched_ab(cells, params, stats) -> dict:
    """Interleaved EDF-vs-FIFO A/B on the PR-2 serving config (32px
    AmoebaNet, buckets (1, 32)) under a fixed 1:3 tight:bulk class mix —
    tight requests carry a 10 s deadline, bulk 60 s, so EDF order lets
    tight jump the bulk backlog while FIFO serves arrival order. Both
    arms run the SAME deterministic mix (ClassMix is RNG-free); per-arm
    per-trial p99s are reduced by median across trials."""
    from mpi4dl_tpu.profiling import percentiles as _pct
    from mpi4dl_tpu.serve import ServingEngine
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    size = 32
    classes = "tight=250ms:99@10s,bulk=2.5s:99@60s"
    mix = {"tight": (1.0, 10.0), "bulk": (3.0, 60.0)}
    trials, requests = 3, 256
    engines = {
        arm: ServingEngine(
            cells, params, stats, example_shape=(size, size, 3),
            buckets=(1, 32), max_wait_s=0.003, max_queue=512,
            default_deadline_s=30.0, slo_classes=classes, scheduler=arm,
        )
        for arm in ("edf", "fifo")
    }
    samples = {
        arm: {"tight_p99": [], "bulk_p99": [], "rps": [], "misses": 0}
        for arm in engines
    }
    try:
        for eng in engines.values():
            eng.start()
        for _ in range(trials):
            for arm, eng in engines.items():
                rep = run_closed_loop(
                    eng, requests, concurrency=64, deadline_s=30.0,
                    class_mix=dict(mix),
                )
                by = rep["by_class"] or {}
                for cls, key in (("tight", "tight_p99"),
                                 ("bulk", "bulk_p99")):
                    p99 = (by.get(cls) or {}).get("latency_s", {}).get("p99")
                    if p99 is not None:
                        samples[arm][key].append(p99)
                samples[arm]["rps"].append(rep["throughput_rps"])
                samples[arm]["misses"] += rep["deadline_misses"]
    finally:
        for eng in engines.values():
            eng.stop()

    def _median(vals):
        return _pct(vals, (50,))["p50"] if vals else None

    arms = {
        arm: {
            "tight_p99_ms": (
                round(_median(s["tight_p99"]) * 1e3, 2)
                if s["tight_p99"] else None
            ),
            "bulk_p99_ms": (
                round(_median(s["bulk_p99"]) * 1e3, 2)
                if s["bulk_p99"] else None
            ),
            "rps": round(_median(s["rps"]), 1) if s["rps"] else None,
            "deadline_misses": s["misses"],
        }
        for arm, s in samples.items()
    }
    out = {
        "classes": classes,
        "mix": "tight:1:10s,bulk:3:60s",
        "trials": trials,
        "requests_per_trial": requests,
        "arms": arms,
    }
    edf, fifo = arms["edf"], arms["fifo"]
    if edf["tight_p99_ms"] and fifo["tight_p99_ms"]:
        out["tight_p99_improved"] = edf["tight_p99_ms"] < fifo["tight_p99_ms"]
        out["tight_p99_ratio"] = round(
            edf["tight_p99_ms"] / fifo["tight_p99_ms"], 3
        )
    if edf["rps"] and fifo["rps"]:
        out["rps_delta_pct"] = round(
            (edf["rps"] - fifo["rps"]) / fifo["rps"] * 100.0, 2
        )
    return out


def _measure_fleet() -> dict:
    """Fleet recovery extra (docs/FLEET.md): ONE fleet — 2 replica
    subprocesses + 1 warm-pool standby behind 2 front-door router
    processes — put through BOTH kill drills under closed-loop load:

    - arm ``replica``: ``kill -9`` a serving replica mid-run; with the
      warm pool on, recovery is a standby promotion (routing flip), so
      ``recovery_s.replica`` is handshake-bound (< 2 s target on CPU vs
      ~7 s warm-up-compile cold), and the pool backfills afterward;
    - arm ``router``: ``kill -9`` a router process mid-run; the client
      fails over (``router_failovers``), the supervisor respawns the
      slot, and the successor replays the journal
      (``journal_replays`` > 0); ``recovery_s.router`` is the router's
      death-to-ready time.

    bench-history trends ``recovery_s.replica`` and
    ``recovery_s.router`` with the regression sign inverted. The
    workers are pinned to the CPU backend: this bench process owns the
    accelerator, and the mechanics under measurement — dispatch,
    failover, journal replay, promotion — are host-side."""
    import signal as _signal
    import threading

    from mpi4dl_tpu.fleet.__main__ import _journal_replays
    from mpi4dl_tpu.fleet.frontdoor import RouterSetClient
    from mpi4dl_tpu.fleet.supervisor import FleetSupervisor
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    n_requests = 600
    sup = FleetSupervisor(
        ["--image-size", "16", "--max-batch", "2"],
        router=None, registry=_REGISTRY,
        replicas=2, max_replicas=2, warm_pool=1,
        routers=2,
        router_args=["--image-size", "16", "--max-attempts", "4",
                     "--inflight-per-replica", "4",
                     "--health-interval", "0.1"],
        env=env,
        reconcile_interval_s=0.1, backoff_base_s=0.1,
        backoff_max_s=0.5, spawn_timeout_s=420.0,
    )
    client = None
    try:
        t0 = time.monotonic()
        sup.start()
        sup.wait_ready(timeout_s=420)
        startup_s = time.monotonic() - t0
        client = RouterSetClient(
            sup.router_submit_urls(), example_shape=(16, 16, 3),
            default_deadline_s=120.0,
        )

        def drill(kill) -> dict:
            rep: dict = {}

            def load():
                rep.update(run_closed_loop(
                    client, n_requests, concurrency=12, deadline_s=120.0,
                ))

            t = threading.Thread(target=load, name="fleet-drill-load")
            t.start()
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if client.stats()["submitted"] >= n_requests // 10:
                    break
                time.sleep(0.01)
            kill()
            t.join(timeout=300)
            return rep

        # Arm 1 — replica kill with the warm pool on: recovery is a
        # promotion, and the pool backfills (cold) afterward.
        rep_a = drill(lambda: os.kill(
            sup.slot_by_index(1).pid, _signal.SIGKILL
        ))
        recovery_replica = sup.last_recovery_s
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if (sup.running_count() == 2 and sup.standby_count() == 1):
                break
            time.sleep(0.2)
        backfilled = sup.standby_count() == 1

        # Arm 2 — router kill: client failover + journal replay on the
        # respawned slot.
        rep_b = drill(lambda: os.kill(
            sup.router_slot_by_index(1).pid, _signal.SIGKILL
        ))
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if sup.running_router_count() == 2:
                break
            time.sleep(0.2)
        recovery_router = sup.last_router_recovery_s
        replays = _journal_replays(sup)
        return {
            "value": round(rep_a["throughput_rps"], 1),
            "unit": "requests/sec through a kill -9 replica drill "
                    "(HTTP front door, warm pool on)",
            "served": rep_a["served"] + rep_b["served"],
            "offered": 2 * n_requests,
            "errors": rep_a["errors"] + rep_b["errors"],
            "router_kill_rps": round(rep_b["throughput_rps"], 1),
            "router_failovers": rep_b.get("router_failovers", 0),
            "journal_replays": replays,
            "promotions": sup.promotions,
            "pool_backfilled": backfilled,
            "restarts": sup.restarts,
            "recovery_s": {
                "replica": (
                    round(recovery_replica, 2)
                    if recovery_replica is not None else None
                ),
                "router": (
                    round(recovery_router, 2)
                    if recovery_router is not None else None
                ),
            },
            "startup_s": round(startup_s, 2),
            "latency_ms": {
                k: round(v * 1e3, 2)
                for k, v in rep_a["latency_s"].items() if v is not None
            },
        }
    finally:
        sup.close()
        if client is not None:
            client.close()


def _measure_incident() -> dict:
    """Incident-engine drill extra (docs/OBSERVABILITY.md "Incidents"):
    the replica kill drill again, but SCORED by the incident engine —
    a standalone :class:`FederatedAggregator` (0.1 s scrape tick, the
    stock :class:`IncidentManager` riding its alert surface) watches
    both replicas while ``chaos.inject("kill:1")`` lands the fault.

    Recorded per ISSUE: ``mttd_s`` (page→incident-open, the open
    record's MTTA), ``mttr_s`` (open→close), and ``blame_correct`` —
    whether the auto-postmortem's first cause names the injected chaos
    op. bench-history trends ``incident.mttd_s`` / ``incident.mttr_s``
    with the regression sign INVERTED (slower detection or recovery
    regresses); rounds that never detect/close omit the field
    (absent-not-zero). Throughput through the fault rides ``value``."""
    import threading

    from mpi4dl_tpu import telemetry
    from mpi4dl_tpu.fleet.chaos import inject, parse_chaos_spec
    from mpi4dl_tpu.fleet.frontdoor import RouterSetClient
    from mpi4dl_tpu.fleet.supervisor import FleetSupervisor
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    repo = os.path.dirname(os.path.abspath(__file__))
    tele = tempfile.mkdtemp(prefix="mpi4dl-bench-incident-")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        MPI4DL_TPU_TELEMETRY_DIR=tele,
    )
    n_requests = 400
    events = telemetry.JsonlWriter(tele, filename="fleet-events.jsonl")
    sup = FleetSupervisor(
        ["--image-size", "16", "--max-batch", "2"],
        router=None, registry=_REGISTRY,
        replicas=2, max_replicas=2, warm_pool=1,
        routers=2,
        router_args=["--image-size", "16", "--max-attempts", "4",
                     "--inflight-per-replica", "4",
                     "--health-interval", "0.1"],
        env=env, events=events,
        reconcile_interval_s=0.1, backoff_base_s=0.1,
        backoff_max_s=0.5, spawn_timeout_s=420.0,
    )
    agg = None
    client = None
    try:
        sup.start()
        sup.wait_ready(timeout_s=420)

        def serving_urls() -> dict:
            urls = {}
            for i in range(3):
                s = sup.slot_by_index(i)
                if (s is not None and s.state == "running"
                        and s.role == "serving" and s.ports
                        and s.ports.get("metrics_port")):
                    urls[s.name] = (
                        f"http://127.0.0.1:{s.ports['metrics_port']}"
                    )
            return urls

        # The watcher: its own aggregator so the drill controls target
        # membership (the supervisor-integrated one deregisters a slot
        # on confirmed death, which this drill reproduces by hand after
        # recovery). Shares the fleet's event log so incident lifecycle
        # events interleave with chaos.injected / elastic.restart.
        agg = telemetry.FederatedAggregator(
            replicas=serving_urls(), events=events,
            interval_s=0.1, timeout_s=0.5,
        )
        agg.incidents.telemetry_dir = tele
        agg.start()

        client = RouterSetClient(
            sup.router_submit_urls(), example_shape=(16, 16, 3),
            default_deadline_s=120.0,
        )
        rep: dict = {}

        def load():
            rep.update(run_closed_loop(
                client, n_requests, concurrency=12, deadline_s=120.0,
            ))

        t = threading.Thread(target=load, name="incident-drill-load")
        t.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if client.stats()["submitted"] >= n_requests // 10:
                break
            time.sleep(0.01)
        t_kill = time.monotonic()
        inject(parse_chaos_spec("kill:1"), sup)

        # Detection: injected fault → replica_unreachable page → open.
        kill_to_open = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if agg.incidents.opened_total > 0:
                kill_to_open = time.monotonic() - t_kill
                break
            time.sleep(0.02)
        mttd = None
        inc = agg.incidents.open_incident
        if inc is not None and isinstance(inc.get("mtta_s"), (int, float)):
            mttd = inc["mtta_s"]
        t.join(timeout=300)

        # Recovery: wait for the promotion/backfill, then swap the
        # scrape set to the post-recovery serving slots — the target
        # swap the supervisor performs on confirmed death + handshake.
        # The next clean scrape resolves the page and closes the
        # incident.
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if sup.running_count() == 2 and serving_urls():
                break
            time.sleep(0.1)
        live = serving_urls()
        for tgt in list(agg.replicas()):
            if tgt.name not in live:
                agg.remove_replica(tgt.name)
        for name, url in live.items():
            agg.add_replica(name, url)
        mttr = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if agg.incidents.closed_total > 0:
                break
            time.sleep(0.02)
        state = agg.incidents.state()
        pm = (state["closed"] or state["open"] or [None])[-1]
        if state["closed"]:
            v = pm["incident"].get("mttr_s")
            if isinstance(v, (int, float)):
                mttr = v
            if mttd is None and isinstance(
                pm["incident"].get("mtta_s"), (int, float)
            ):
                mttd = pm["incident"]["mtta_s"]
        cause = (pm or {}).get("first_cause") or {}
        blame_correct = bool(
            cause.get("event") == "chaos.injected"
            and str((cause.get("attrs") or {}).get("op", "")).startswith(
                "kill"
            )
        )
        out = {
            "value": round(rep.get("throughput_rps", 0.0), 1),
            "unit": "requests/sec through a chaos kill drill scored by "
                    "the incident engine",
            "served": rep.get("served"),
            "errors": rep.get("errors"),
            "incidents_opened": agg.incidents.opened_total,
            "incidents_closed": agg.incidents.closed_total,
            "blame_correct": blame_correct,
            "first_cause": cause.get("label"),
        }
        # Absent-not-zero: a round that never detected (or never
        # closed) records NO latency rather than a flattering 0.
        if mttd is not None:
            out["mttd_s"] = round(mttd, 3)
        if kill_to_open is not None:
            out["kill_to_open_s"] = round(kill_to_open, 3)
        if mttr is not None:
            out["mttr_s"] = round(mttr, 3)
        return out
    finally:
        if agg is not None:
            agg.close()
        sup.close()
        if client is not None:
            client.close()


def _measure_coldstart() -> dict:
    """Cold-start decomposition extra (docs/OBSERVABILITY.md "Cold
    start"): two single-replica fleets, one ``kill -9`` each —

    - arm ``cold``: no warm pool — recovery is a full respawn, and the
      worker's ready handshake attributes every second of it across
      ``spawn/import/construct/compile/warm/ready``;
    - arm ``promote``: warm pool of 1 — recovery is a standby
      promotion, attributed honestly as all ``ready`` (routing flip)
      with ``compile == 0``: the phase evidence the pool's idle RAM
      buys the skipped phases.

    bench-history trends ``recovery_s.{cold,promote}`` and every
    ``phase_s.{arm}.{phase}`` with the INVERTED sign; the headline
    ``value`` is the promotion speedup (cold / promote recovery, normal
    sign). The worker ledger dumps collected before teardown feed
    ``analyze coldstart`` — the top executables by compile seconds land
    in ``manifest``."""
    import signal as _signal

    from mpi4dl_tpu.analysis.coldstart import build_manifest
    from mpi4dl_tpu.fleet.supervisor import FleetSupervisor

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )

    def drill(warm_pool: int) -> dict:
        # --max-batch 4 → three serve buckets (1, 2, 4): the manifest's
        # top-3 ranking has three real executables to name.
        sup = FleetSupervisor(
            ["--image-size", "16", "--max-batch", "4"],
            router=None, registry=_REGISTRY,
            replicas=1, max_replicas=1, warm_pool=warm_pool,
            env=env,
            reconcile_interval_s=0.1, backoff_base_s=0.1,
            backoff_max_s=0.5, spawn_timeout_s=420.0,
        )
        try:
            sup.start()
            sup.wait_ready(timeout_s=420)
            os.kill(sup.slot_by_index(0).pid, _signal.SIGKILL)
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                if sup.last_recovery_s is not None and sup.running_count() >= 1:
                    break
                time.sleep(0.05)
            # The replacement's ledger dump (written next to its ready
            # file) must be read BEFORE close() tears the run dir down.
            ledgers = []
            for i in range(2):
                slot = sup.slot_by_index(i)
                path = (slot.ports or {}).get("ledger") if slot else None
                if path and os.path.exists(path):
                    ledgers.append(path)
            manifest = (
                build_manifest(ledgers, top=3) if ledgers else None
            )
            return {
                "recovery_s": sup.last_recovery_s,
                "phases": dict(sup.last_recovery_phases or {}),
                "promotions": sup.promotions,
                "manifest": manifest,
            }
        finally:
            sup.close()

    cold = drill(0)
    promote = drill(1)
    manifest = promote["manifest"] or cold["manifest"]
    speedup = None
    if cold["recovery_s"] and promote["recovery_s"]:
        speedup = round(cold["recovery_s"] / promote["recovery_s"], 1)
    return {
        "value": speedup,
        "unit": "x promotion speedup (cold respawn s / warm-pool "
                "promote s, kill -9 to routable)",
        "recovery_s": {
            "cold": (
                round(cold["recovery_s"], 2)
                if cold["recovery_s"] is not None else None
            ),
            "promote": (
                round(promote["recovery_s"], 2)
                if promote["recovery_s"] is not None else None
            ),
        },
        "phases": {
            "cold": {k: round(v, 3) for k, v in cold["phases"].items()},
            "promote": {
                k: round(v, 3) for k, v in promote["phases"].items()
            },
        },
        "promotions": promote["promotions"],
        "top_executables": [
            {
                "executable": g["executable"],
                "fingerprint": g["fingerprint"],
                "compile_s": g["compile_s"],
            }
            for g in (manifest or {}).get("executables", [])
        ],
    }


def _measure_multitenant() -> dict:
    """Multi-tenant QoS extra (docs/SERVING.md "Multi-tenancy"): one
    small engine, three closed-loop rounds —

    - ``off``: tenancy disabled — the zero-overhead baseline;
    - ``solo``: tenancy on, the victim tenant alone — its clean p99;
    - ``flood``: a 10:1 bully:victim noisy-neighbor flood through the
      deficit-weighted-round-robin batch fill.

    bench-history trends ``victim_p99_ratio`` (flood p99 / solo p99,
    INVERTED sign — a growing ratio means tenant isolation regressed)
    and ``fairness_index`` (Jain's index over per-tenant served/offered,
    normal sign — falling fairness regresses); ``overhead_pct`` records
    the tenancy-on tax vs the off baseline (docs target: within 2%)."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve import ServingEngine
    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.utils import get_depth

    size = 16
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=size // 4
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )

    def mk_engine(**kw):
        return ServingEngine(
            cells, params, stats, example_shape=(size, size, 3),
            max_batch=8, max_queue=512, default_deadline_s=60.0, **kw
        )

    n = 512
    eng_off = mk_engine()
    eng_off.start()
    try:
        # Warm-up pass first: bucket compiles and allocator churn must
        # not land inside either arm of the ON/OFF overhead comparison.
        run_closed_loop(eng_off, 64, concurrency=32, deadline_s=60.0)
        off = run_closed_loop(eng_off, n, concurrency=32, deadline_s=60.0)
    finally:
        eng_off.stop()

    eng = mk_engine(tenants="victim=none,bully=none", registry=_REGISTRY)
    eng.start()
    try:
        run_closed_loop(
            eng, 64, concurrency=32, deadline_s=60.0,
            tenant_mix={"victim": 1.0},
        )
        solo = run_closed_loop(
            eng, n, concurrency=32, deadline_s=60.0,
            tenant_mix={"victim": 1.0},
        )
        flood = run_closed_loop(
            eng, n, concurrency=32, deadline_s=60.0,
            tenant_mix={"bully": 10.0, "victim": 1.0},
        )
    finally:
        eng.stop()

    solo_p99 = solo["by_tenant"]["victim"]["latency_s"]["p99"]
    flood_p99 = flood["by_tenant"]["victim"]["latency_s"]["p99"]
    served = {t: rec["served"] for t, rec in flood["by_tenant"].items()}
    offered = {"bully": 10.0, "victim": 1.0}
    xs = [served[t] / offered[t] for t in served if t in offered]
    jain = (
        sum(xs) ** 2 / (len(xs) * sum(x * x for x in xs)) if any(xs) else 0.0
    )
    on_rps = solo["throughput_rps"]
    off_rps = off["throughput_rps"]
    return {
        "value": round(on_rps, 1),
        "unit": "requests/sec with tenancy on (single tenant)",
        "off_rps": round(off_rps, 1),
        "overhead_pct": round((off_rps - on_rps) / off_rps * 100.0, 2),
        # Noisy-neighbor isolation: how much the 10:1 flood inflates the
        # victim's p99 over its solo baseline (1.0 == perfect isolation).
        "victim_p99_ratio": round(flood_p99 / max(solo_p99, 1e-9), 3),
        "victim_p99_ms": {
            "solo": round(solo_p99 * 1e3, 2),
            "flood": round(flood_p99 * 1e3, 2),
        },
        "fairness_index": round(jain, 4),
        "served_by_tenant": served,
        "deadline_misses": flood["deadline_misses"],
        "rejected_quota": flood["rejected_quota"],
    }


def _measure_numerics() -> dict:
    """Numerics sentinel extra (docs/OBSERVABILITY.md "Numerics"): one
    small engine, two closed-loop arms plus a corrupt drill —

    - ``off``: no canary sentinel — the zero-overhead baseline;
    - ``on``: sentinel probing every 0.2s through the real dispatch
      path (the deployment posture; docs target: within 2% rps);
    - the drill: flip 3 bits in the live param buffer and time
      corruption → fence (``canary.failure`` callback).

    bench-history trends ``rps_overhead_pct`` and ``detect_s``, both
    INVERTED — a grown canary tax or a slower detection regresses."""
    import threading

    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve import ServingEngine
    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.utils import get_depth

    size = 16
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=size // 4
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )

    def mk_engine(**kw):
        return ServingEngine(
            cells, params, stats, example_shape=(size, size, 3),
            max_batch=8, max_queue=512, default_deadline_s=60.0, **kw
        )

    n = 512
    eng_off = mk_engine()
    eng_off.start()
    try:
        # Warm-up pass first (same discipline as the multitenant A/B):
        # compiles and allocator churn stay out of both arms.
        run_closed_loop(eng_off, 64, concurrency=32, deadline_s=60.0)
        off = run_closed_loop(eng_off, n, concurrency=32, deadline_s=60.0)
    finally:
        eng_off.stop()

    interval = 0.2
    eng = mk_engine(canary_interval_s=interval, registry=_REGISTRY)
    fence_at: dict = {}
    fenced = threading.Event()

    def _on_failure(attrs):
        fence_at.setdefault("t", time.perf_counter())
        fence_at.setdefault("check", attrs.get("check"))
        fenced.set()

    eng.canary.on_failure(_on_failure)
    eng.start()
    try:
        run_closed_loop(eng, 64, concurrency=32, deadline_s=60.0)
        on = run_closed_loop(eng, n, concurrency=32, deadline_s=60.0)
        # Corrupt drill AFTER the measured arm: detection latency is
        # the metric here, the fenced engine's rps is not.
        t0 = time.perf_counter()
        forensics = eng.corrupt_params(bits=3)
        detected = fenced.wait(timeout=max(10.0, 20 * interval))
        view = eng.canary.view()
    finally:
        eng.stop()

    on_rps = on["throughput_rps"]
    off_rps = off["throughput_rps"]
    entry = {
        "value": round(on_rps, 1),
        "unit": "requests/sec with canary sentinel on",
        "off_rps": round(off_rps, 1),
        "rps_overhead_pct": round((off_rps - on_rps) / off_rps * 100.0, 2),
        "canary_interval_s": interval,
        "detected": bool(detected),
        "detect_check": fence_at.get("check"),
        "corrupt": {"bits": 3, "leaf": forensics.get("leaf")},
        "canary_checks": view.get("checks"),
        "canary_failures": view.get("failures"),
    }
    if detected:
        entry["detect_s"] = round(fence_at["t"] - t0, 3)
    return entry


def _measure_sp_overlap() -> dict:
    """SP 2×2 halo/compute-overlap A/B extra: run the spatially-
    partitioned train step with the monolithic AND the decomposed conv
    impl (``MPI4DL_TPU_CONV_OVERLAP``) and embed both arms' measured
    ``trace_overlap_ratio`` + step time in the result line — the number
    ``analyze bench-history`` trends (a falling ratio regresses). Runs as
    a subprocess on a 4-virtual-device CPU mesh: this bench process owns
    the accelerator (one chip — no 2×2 tile mesh exists on it), and the
    property under measurement is the compiled program's schedule freedom,
    which the CPU thunk executor exhibits the same way."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    # Each arm pins its own impl; an inherited process-wide override
    # would silently collapse the A/B into one arm measured twice.
    env.pop("MPI4DL_TPU_CONV_OVERLAP", None)
    proc = subprocess.run(
        [sys.executable, "-m", "mpi4dl_tpu.analyze", "sp-overlap",
         "--size", "64", "--steps", "4", "--trials", "3", "--json", "-"],
        env=env, capture_output=True, text=True, timeout=900, cwd=repo,
    )
    line = next(
        (ln for ln in reversed(proc.stdout.splitlines())
         if ln.startswith("{")), None,
    )
    if line is None:
        raise RuntimeError(
            f"sp-overlap emitted no JSON (rc={proc.returncode}): "
            f"{proc.stderr[-300:]}"
        )
    out = json.loads(line)
    out["rc"] = proc.returncode
    return out


def _measure_serving_sharded() -> dict:
    """Sharded-serving overlap A/B extra: a 2×2 spatially-sharded engine
    under closed-loop load with the monolithic AND decomposed conv impl,
    embedding both arms' measured ``trace_overlap_ratio`` + per-request
    latency (``analyze bench-history`` trends the ratio normal-sign and
    p99 inverted). Same subprocess rationale as ``_measure_sp_overlap``:
    the 4-virtual-device CPU tile mesh must exist regardless of the
    bench headline's backend, and the property under measurement is the
    compiled schedule's freedom, not CPU wall-clock."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    env.pop("MPI4DL_TPU_CONV_OVERLAP", None)
    proc = subprocess.run(
        [sys.executable, "-m", "mpi4dl_tpu.analyze", "serving-sharded",
         "--size", "32", "--requests", "64", "--trials", "2",
         "--json", "-"],
        env=env, capture_output=True, text=True, timeout=900, cwd=repo,
    )
    line = next(
        (ln for ln in reversed(proc.stdout.splitlines())
         if ln.startswith("{")), None,
    )
    if line is None:
        raise RuntimeError(
            f"serving-sharded emitted no JSON (rc={proc.returncode}): "
            f"{proc.stderr[-300:]}"
        )
    out = json.loads(line)
    out["rc"] = proc.returncode
    return out


def _measure_pipeline() -> dict:
    """Pipeline schedule A/B extra: the LP pipeline train step under the
    gpipe AND interleaved-1f1b schedules (``analyze pipeline``), embedding
    both arms' measured ``pipeline_bubble_fraction`` + img/s in the result
    line — bench-history trends the bubble per arm with the INVERTED sign
    (a grown bubble regresses) and img/s with the normal sign. Same
    subprocess rationale as ``_measure_sp_overlap``: the pipe mesh must
    exist regardless of the bench headline's backend, and the property
    under measurement — which stage-switch slots the compiled schedule
    executes — is backend-independent."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    # trials=1: the bubble is slot-counted off the compiled schedule's
    # branch executions — deterministic, unlike the wall-clock ratios the
    # overlap A/Bs pool across interleaved trials — so extra trials only
    # buy img/s averaging at real CPU cost.
    proc = subprocess.run(
        [sys.executable, "-m", "mpi4dl_tpu.analyze", "pipeline",
         "--steps", "3", "--trials", "1", "--require-improvement",
         "--json", "-"],
        env=env, capture_output=True, text=True, timeout=900, cwd=repo,
    )
    line = next(
        (ln for ln in reversed(proc.stdout.splitlines())
         if ln.startswith("{")), None,
    )
    if line is None:
        raise RuntimeError(
            f"analyze pipeline emitted no JSON (rc={proc.returncode}): "
            f"{proc.stderr[-300:]}"
        )
    out = json.loads(line)
    out["rc"] = proc.returncode
    return out


def _measure_tiled_gigapixel() -> dict:
    """Gigapixel tiled-inference extra (serve/tiled.py): (a) a peak
    feasible px WALK — the largest square image one chip serves through
    the halo-correct tile stream, each success recorded with the tile
    executable's compile-time peak so the round file shows bounded-not-
    full-image memory; (b) per-request latency at a FIXED large size
    under a small closed loop, with the tile-count/stitch breakdown.
    bench-history trends ``tiled_gigapixel.peak_px`` (normal sign — a
    shrunk capability regresses) and ``tiled_gigapixel.latency_p99_ms``
    (INVERTED — slower gigapixel requests regress). Sizes scale by
    backend: CPU walks 256→512 so the extra stays in budget; a TPU round
    starts at 8192 (past the single-chip monolithic wall) by default.
    ``BENCH_TILED_PX``/``BENCH_TILED_TILE``/``BENCH_TILED_WALK``
    override."""
    import jax
    import numpy as np

    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.serve.tiled import synthetic_tiled_engine

    on_cpu = jax.default_backend() == "cpu"
    fixed_px = int(
        os.environ.get("BENCH_TILED_PX", "256" if on_cpu else "8192")
    )
    tile = int(
        os.environ.get("BENCH_TILED_TILE", str(max(64, fixed_px // 4)))
    )
    walk_steps = int(os.environ.get("BENCH_TILED_WALK", "1"))
    engine_kw = dict(
        tile=tile, max_queue=8, calib_batches=1,
        default_deadline_s=1200.0,
    )
    entry = {
        "unit": "square image side, one chip, tiled stream",
        "tile": tile,
        "walk": [],
        "peak_px": None,
    }

    # (a) Peak feasible px walk: double from the fixed size; each
    # success is recorded immediately (the next, larger, attempt is
    # expected to eventually fail — on TPU with RESOURCE_EXHAUSTED at
    # the head, on CPU only by budget).
    px = fixed_px
    for _ in range(walk_steps + 1):
        t0 = time.time()
        step = {"px": px}
        try:
            eng = synthetic_tiled_engine(px, **engine_kw)
            try:
                eng.start()
                fut = eng.submit(
                    np.zeros((px, px, 3), np.float32), deadline_s=1200.0
                )
                fut.result(timeout=1200.0)
                tile_e = eng.memory_ledger.get("serve_tiled", bucket=1)
                head_e = eng.memory_ledger.get("serve_tiled_head")
                step.update(
                    serve_s=round(time.time() - t0, 2),
                    tile_peak_hbm_bytes=(
                        tile_e.get("peak_bytes") if tile_e else None
                    ),
                    head_peak_hbm_bytes=(
                        head_e.get("peak_bytes") if head_e else None
                    ),
                )
                entry["peak_px"] = px
            finally:
                eng.stop()
        except Exception as e:  # noqa: BLE001 — the walk's whole point
            # is to find the failure edge without losing the peak
            step["error"] = f"{type(e).__name__}: {str(e)[:160]}"
            entry["walk"].append(step)
            break
        entry["walk"].append(step)
        px *= 2

    # (b) Latency at the fixed size: a small closed loop (gigapixel
    # traffic is low-rps by nature; the tail percentiles and the
    # tile/stitch split are the serving numbers that matter).
    eng = synthetic_tiled_engine(fixed_px, **engine_kw)
    try:
        eng.start()
        rep = run_closed_loop(
            eng, 6 if on_cpu else 4, concurrency=2, deadline_s=1200.0
        )
    finally:
        eng.stop()
    lint = eng.lint_report()
    entry.update(
        image_px=fixed_px,
        latency_ms={
            k: round(v * 1e3, 1)
            for k, v in rep["latency_s"].items() if v is not None
        },
        served=rep["served"],
        errors=rep["errors"],
        deadline_misses=rep["deadline_misses"],
        tiled=rep["engine"].get("tiled"),
        lint_ok=lint.ok,
    )
    return entry


def _serving_attribution(trace_dir, lint_report) -> "dict | None":
    """Measured device-time attribution of the serving load run
    (analysis/trace.py over the engine's own ``mpi4dl_serve_batch``
    annotations), cross-checked against the single-chip static lint.
    Advisory: failures degrade to an error note. ``BENCH_ATTRIBUTION=0``
    disables (checked by the caller, which then skips the trace too)."""
    import shutil

    try:
        from mpi4dl_tpu.analysis.trace import (
            analyze_trace_dir,
            crosscheck_overlap,
            publish_attribution,
        )

        summary = analyze_trace_dir(
            trace_dir, step_name="mpi4dl_serve_batch"
        )
        if _REGISTRY is not None:
            publish_attribution(summary, _REGISTRY, program="serve_batch")
        checks = crosscheck_overlap(lint_report, summary)
        return {
            "n_steps": summary["n_steps"],
            "per_step_mean": summary["per_step_mean"],
            "range": summary["range"],
            "overlap": summary["collective"],
            "crosscheck": [f.as_dict() for f in checks],
        }
    except Exception as e:  # noqa: BLE001 — advisory metrics only
        return {"error": f"{type(e).__name__}: {str(e)[:160]}"}
    finally:
        shutil.rmtree(trace_dir, ignore_errors=True)


def _hlo_overlap_metrics() -> "dict | None":
    """Static overlap/bytes/peak-HBM metrics of the LAST measured program,
    recorded into the emitted result line (and thus ``BENCH_*.json``) via
    the hlolint analyzer. ``BENCH_HLO=0`` disables; failures degrade to an
    error note — the analysis must never cost a measured headline."""
    if os.environ.get("BENCH_HLO", "1") == "0" or not _LAST_RUN:
        return None
    try:
        import jax

        from mpi4dl_tpu.analysis import analyze_compiled

        tr = _LAST_RUN["trainer"]
        compiled = tr._jit_step.lower(
            _LAST_RUN["state"], _LAST_RUN["xs"], _LAST_RUN["ys"]
        ).compile()
        rep = analyze_compiled(
            compiled,
            remat=tr.remat_report(),
            platform=jax.devices()[0].platform,
            config={"program": "train_step"},
        )
        if _REGISTRY is not None:
            from mpi4dl_tpu.analysis.metrics import publish_report

            publish_report(rep, _REGISTRY)
            # Footprint ledger: the already-compiled train step's peak
            # under program_peak_hbm_bytes (zero extra compile).
            from mpi4dl_tpu.telemetry.memory import FootprintLedger

            FootprintLedger(registry=_REGISTRY).record_compiled(
                "train_step", compiled
            )
        # The static report is the "should overlap" side the measured
        # trace attribution cross-checks against (_trace_attribution).
        _LAST_RUN["lint_report"] = rep
        # Static cost model (docs/ANALYSIS.md "Reading the cost model"):
        # price the same collective inventory under the live CPU prior and
        # the ICI prior, so BENCH_*.json carries the predicted comms time
        # and overlap ceiling next to the measured numbers and
        # `analyze bench-history` can trend predicted-vs-measured drift.
        from mpi4dl_tpu.analysis.costmodel import (
            predict_from_report,
            publish_prediction,
        )

        costmodel = {}
        for ic in ("cpu", "ici"):
            pred = predict_from_report(rep, interconnect=ic)
            costmodel[ic] = {
                "comms_s": pred["comms_s"],
                "exposed_s": pred["exposed_s"],
                "predicted_overlap_ratio": pred["overlap_ratio"],
                "overlap_claim": pred["overlap_claim"],
            }
            if _REGISTRY is not None:
                publish_prediction(pred, _REGISTRY, program="train_step")
            if ic == "cpu":
                # The prior matching the runtime we actually measure on;
                # _trace_attribution cross-checks drift against this one.
                _LAST_RUN["costmodel_pred"] = pred
        return {
            "costmodel": costmodel,
            "inventory": {k: v for k, v in rep.inventory.items() if v},
            "total_collective_bytes": rep.overlap["total_bytes"],
            "bytes_by_op": rep.overlap["bytes_by_op"],
            "async_pairs": rep.overlap["async_pairs"],
            "zero_overlap": len(rep.overlap["zero_overlap"]),
            "min_compute_between": rep.overlap["min_compute_between"],
            "peak_hbm_bytes": (
                rep.memory.get("peak_bytes") if rep.memory else None
            ),
            "findings": [
                f for f in rep.findings if f["severity"] != "info"
            ],
        }
    except Exception as e:  # noqa: BLE001 — advisory metrics only
        return {"error": f"{type(e).__name__}: {str(e)[:160]}"}


def _trace_attribution() -> "dict | None":
    """MEASURED device-time attribution of the headline train step: a
    2-step XProf capture (Trainer.capture_trace_attribution), bucketed
    compute/collective/transfer/host-gap + the measured-overlap verdict,
    cross-checked against the static hlolint report when one landed.
    BENCH_*.json thereby records the measured overlap trajectory next to
    the static prediction. ``BENCH_ATTRIBUTION=0`` disables; failures
    degrade to an error note."""
    if (
        os.environ.get("BENCH_ATTRIBUTION", "1") == "0"
        or not _LAST_RUN
    ):
        return None
    import shutil

    logdir = tempfile.mkdtemp(prefix="mpi4dl-bench-train-trace-")
    try:
        tr = _LAST_RUN["trainer"]
        state, summary = tr.capture_trace_attribution(
            _LAST_RUN["state"], _LAST_RUN["xs"], _LAST_RUN["ys"],
            steps=2, logdir=logdir, registry=_REGISTRY,
            program="train_step",
        )
        _LAST_RUN["state"] = state
        from mpi4dl_tpu.ops.layers import conv_overlap_impl

        out = {
            "n_steps": summary["n_steps"],
            "per_step_mean": summary["per_step_mean"],
            "overlap": summary["collective"],
            # Which spatial-conv impl produced this attribution: the
            # monolithic/decomposed A/B (sp2x2_overlap extra) must be
            # attributable from the result line alone.
            "conv_impl": conv_overlap_impl(),
        }
        lint_rep = _LAST_RUN.get("lint_report")
        if lint_rep is not None:
            from mpi4dl_tpu.analysis.trace import crosscheck_overlap

            out["crosscheck"] = [
                f.as_dict() for f in crosscheck_overlap(lint_rep, summary)
            ]
        pred = _LAST_RUN.get("costmodel_pred")
        if pred is not None:
            from mpi4dl_tpu.analysis.costmodel import crosscheck_cost_model

            measured = summary["collective"].get("overlap_ratio")
            out["costmodel"] = {
                "interconnect": pred["interconnect"],
                "predicted_overlap_ratio": pred["overlap_ratio"],
                "overlap_claim": pred["overlap_claim"],
                # Drift is only meaningful when the model makes an overlap
                # claim (async collectives present); the CPU mesh compiles
                # sync-only programs, so bench lines record null there and
                # the series starts populating on the first ICI run.
                "overlap_drift": (
                    abs(float(measured) - float(pred["overlap_ratio"]))
                    if pred["overlap_claim"] and measured is not None
                    else None
                ),
                "crosscheck": [
                    f.as_dict()
                    for f in crosscheck_cost_model(
                        pred, measured_overlap=measured
                    )
                ],
            }
        return out
    except Exception as e:  # noqa: BLE001 — advisory metrics only
        return {"error": f"{type(e).__name__}: {str(e)[:160]}"}
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


def main():
    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    _budget()  # a malformed BENCH_TIME_BUDGET must fail before, not after,
    # the headline measurement pays its multi-minute compile

    from mpi4dl_tpu.utils import apply_platform_env, enable_compilation_cache

    apply_platform_env()  # honor JAX_PLATFORMS even under the axon plugin
    enable_compilation_cache()  # warm-cache compiles make the suite fit any
    # driver budget (first-ever run still pays them; the budget skips extras)

    from mpi4dl_tpu import telemetry

    global _REGISTRY, _TELEMETRY_LOG
    _REGISTRY = telemetry.MetricsRegistry()
    _TELEMETRY_LOG = telemetry.JsonlWriter()  # MPI4DL_TPU_TELEMETRY_DIR-gated

    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.flops import mfu, train_flops_per_image
    from mpi4dl_tpu.models.amoebanet import amoebanetd
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.utils import get_depth

    platform = jax.devices()[0].platform
    on_cpu = platform == "cpu"
    image_size = int(os.environ.get("BENCH_IMAGE_SIZE", "1024"))
    batch = int(os.environ.get("BENCH_BATCH", "2"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    which = os.environ.get("BENCH_MODEL", "all")
    if which not in ("resnet", "amoebanet", "all"):
        raise ValueError(f"BENCH_MODEL must be resnet|amoebanet|all, got {which!r}")
    warmup = 2
    if on_cpu and "BENCH_IMAGE_SIZE" not in os.environ:
        image_size, steps = 128, 3  # keep the CPU smoke path tractable

    dtype = jnp.float32 if on_cpu else jnp.bfloat16
    # "scan" remat: ResNet-110 @1024px stores ~64G of activations with no
    # remat — far beyond one chip's HBM — and the scan policy (one compiled
    # body per repeated stage, compact un-padded residuals, scheduling
    # barriers) trains 2.4x faster than per-cell jax.checkpoint on top of
    # fitting (see Trainer.__init__ docstring for measurements).
    # "scan_save" additionally keeps conv outputs (~2 bytes/pixel-channel)
    # to skip the backward's forward-recompute; it fits up to ~2M pixels
    # per example on one chip — try it first, fall back to "scan" on OOM.
    remat_pref = os.environ.get("BENCH_REMAT")
    # ResNet: cell_save first (fastest, most memory), leaner scan policies
    # on OOM (2048px+). AmoebaNet: scan_save first — compiling its 24 big
    # per-cell graphs (cell_save) crashes the bench runtime's compile
    # helper outright, while the scanned form (3 stacked normal-cell
    # bodies) compiles fine.
    remats = [remat_pref] if remat_pref else ["cell_save", "scan_save", "scan"]
    amoeba_remats = [remat_pref] if remat_pref else ["scan_save", "scan"]
    # >=2048px: cell_save/scan_save reproducibly kill the remote-compile
    # helper (PERF.md r3 #1) — paying those failed compiles (~minutes each)
    # on every run wastes the driver's budget; start at the policy that fits.
    big_remats = [remat_pref] if remat_pref else ["scan"]

    def remats_for(size, base):
        return base if size < 2048 else big_remats

    extras: dict = {}
    # Packed activation layout (ops/packed.py): measured win on TPU;
    # BENCH_LAYOUT=nhwc reverts to the stock layout for A/B.
    layout = os.environ.get("BENCH_LAYOUT", "packed" if not on_cpu else "nhwc")

    def measure_resnet(size, b, baseline):
        """One ResNet-110 point: measure, plus MFU on the LOGICAL model —
        the packed layout executes more device FLOPs by design and must
        not flatter the utilization number."""
        depth = get_depth(2, 12)  # 110 — the reference benchmark's ResNet
        cells = get_resnet_v2(
            depth=depth, num_classes=10, pool_kernel=size // 4,
            layout=layout, dtype=dtype,
        )
        ips, remat, steps_summary = _train_throughput(
            cells, size, b, steps, warmup, dtype, remats_for(size, remats)
        )
        logical = get_resnet_v2(
            depth=depth, num_classes=10, pool_kernel=size // 4, dtype=dtype
        )
        util = mfu(
            ips,
            train_flops_per_image(logical, size, dtype),
            n_devices=jax.device_count(),
        )
        return {
            "value": round(ips, 3),
            "remat": remat,
            "mfu": round(util, 4) if util is not None else None,
            "step_time_s": _step_percentiles(steps_summary),
            "vs_baseline": round(ips / baseline, 3),
        }

    layers, filters = (18, 416) if not on_cpu else (6, 64)

    def measure_amoeba(size, b):
        """One AmoebaNet-D point (the reference's headline model,
        benchmark-default 18 layers / 416 filters). >=2048px with bs>1
        runs as bs-1 scanned chunks (gradient accumulation, GEMS --times
        chunk semantics): the unchunked program reproducibly kills the
        remote-compile helper at EVERY remat policy while bs=1 compiles
        and runs (docs/PERF.md round 3). BENCH_NO_ACCUM=1 reverts."""
        cells = amoebanetd(
            num_classes=10, num_layers=layers, num_filters=filters,
            dtype=dtype,
        )
        accum = (
            b if size >= 2048 and b > 1
            and not os.environ.get("BENCH_NO_ACCUM") else 1
        )
        remats = remats_for(size, amoeba_remats)
        budget_default = (
            size >= 2048
            and not remat_pref
            and "MPI4DL_TPU_SAVE_BUDGET_MB" not in os.environ
        )
        if budget_default:
            # Budgeted scan_save at >=2048: the full save set OOMs but a
            # 6000 MB grant compiles and measured +3% over plain "scan"
            # twice across rounds (r4: 1.249 vs 1.215, r5: 1.447 vs
            # 1.400 — docs/PERF.md round 5); "scan" stays the OOM
            # fallback.
            os.environ["MPI4DL_TPU_SAVE_BUDGET_MB"] = "6000"
            remats = ["scan_save", "scan"]
        try:
            ips, remat, steps_summary = _train_throughput(
                cells, size, b, steps, warmup, dtype,
                remats, grad_accum=accum,
            )
        finally:
            if budget_default:
                # pop, not del: anything inside _train_throughput clearing
                # the variable must not turn cleanup into a KeyError
                # (ADVICE r5; matches the scanq cleanup below).
                os.environ.pop("MPI4DL_TPU_SAVE_BUDGET_MB", None)
        util = mfu(
            ips, train_flops_per_image(cells, size, dtype),
            n_devices=jax.device_count(),
        )
        entry = {
            "value": round(ips, 3),
            "remat": remat,
            "mfu": round(util, 4) if util is not None else None,
            "step_time_s": _step_percentiles(steps_summary),
        }
        if accum > 1:
            entry["grad_accum"] = accum
            # ADVICE r3: vs_baseline compares against the reference's
            # full-batch number while the measured run used bs-1 chunks
            # with per-chunk BatchNorm — say so in the entry itself.
            entry["note"] = (
                f"bs-{b // accum} chunks x{accum} (GEMS --times semantics, "
                "per-chunk BN) vs the reference's full-batch number"
            )
        base = AMOEBA_BASELINE.get((size, b))
        if base:
            entry["vs_baseline"] = round(ips / base, 3)
        return entry

    headline_error = None

    # --- Headline ----------------------------------------------------------
    # AmoebaNet-D @1024 bs2 — the reference's headline model (BASELINE.json
    # configs are AmoebaNet-centric; ref best ~3.0 img/s). BENCH_MODEL=
    # resnet keeps the previous ResNet-110 headline instead.
    try:
        if which in ("amoebanet", "all"):
            h_size, h_b = (image_size, batch) if not on_cpu else (64, 2)
            entry = dict(measure_amoeba(h_size, h_b))
            entry.setdefault("vs_baseline", None)
            _RESULT.update(
                metric=f"amoebanetd_{h_size}px_bs{h_b}_train_{platform}",
                unit="images/sec",
                **entry,
            )
        else:
            entry = measure_resnet(image_size, batch, RESNET_BASELINE)
            _RESULT.update(
                metric=f"resnet110_{image_size}px_bs{batch}_train_{platform}",
                unit="images/sec",
                **entry,
            )
        _emit()  # the driver has its number from this moment on
        hlo = _hlo_overlap_metrics()
        if hlo is not None:
            _RESULT["hlo"] = hlo
            _emit()
        attribution = _trace_attribution()
        if attribution is not None:
            _RESULT["attribution"] = attribution
            _emit()
    except Exception as e:  # noqa: BLE001 — extras may still succeed
        headline_error = f"{type(e).__name__}: {str(e)[:200]}"
        # Record in the result dict, not just a comment line: if an
        # extra later gets promoted, the JSON must still show that the
        # headline itself regressed.
        _RESULT["headline_error"] = headline_error
        print(f"# headline failed: {headline_error}", flush=True)

    def run_extra(tag, fn, est_seconds=300.0):
        """Run one extra under the budget; record + re-emit either way.
        If no headline landed yet, a successful extra is promoted to the
        headline on the spot — every emitted line has a real value."""
        if _remaining() < est_seconds:
            extras[tag] = {
                "skipped": f"insufficient budget: {int(_remaining())}s of "
                f"{int(_budget())}s left, estimated need {int(est_seconds)}s"
            }
        else:
            try:
                extras[tag] = fn()
            except Exception as e:  # noqa: BLE001 — extras never kill the line
                extras[tag] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
        if _RESULT.get("metric") is None and extras[tag].get("value") is not None:
            _RESULT.update(
                metric=f"{tag}_train_{platform}",
                unit="images/sec",
                **extras[tag],
            )
            _RESULT.setdefault("vs_baseline", None)  # documented line shape
        _RESULT["extras"] = extras
        if _RESULT.get("metric"):
            _emit()

    # --- Extras, cheapest-win first, each one re-emitting ------------------
    if which in ("resnet", "all") and not on_cpu:
        # est_seconds below are WARM-cache figures (the persistent
        # compilation cache makes reruns 3-5x cheaper than first-ever
        # compiles). Underestimating a cold run is the safe direction:
        # the budget only gates STARTING an extra, every completed
        # milestone is already emitted, and a driver-side kill therefore
        # loses nothing — whereas overestimating silently skips extras a
        # warm run had plenty of time for.
        if which == "all":
            # The other model family's @1024 point (ref ResNet best ~3.1).
            run_extra(
                f"resnet110_{image_size}px_bs{batch}",
                lambda: measure_resnet(image_size, batch, RESNET_BASELINE),
                est_seconds=300.0,
            )
        # High-res point (BASELINE.md: ref ResNet@2048 SP best ~1.0 img/s
        # bs=1; bs=2 OOMs every published scheme).
        run_extra(
            "resnet110_2048px_bs1",
            lambda: measure_resnet(2048, 1, RESNET_2048_BASELINE),
            est_seconds=200.0,
        )
    elif which == "all" and on_cpu:
        run_extra(
            f"resnet110_{image_size}px_bs{batch}",
            lambda: measure_resnet(image_size, batch, RESNET_BASELINE),
            est_seconds=120.0,
        )

    if which in ("amoebanet", "all") and not on_cpu:
        for size, b in [(2048, 2), (2048, 1)]:
            if (size, b) == (h_size, h_b):
                continue  # already the headline (e.g. BENCH_IMAGE_SIZE=2048)
            run_extra(
                f"amoebanetd_{size}px_bs{b}",
                functools.partial(measure_amoeba, size, b),
                est_seconds=300.0,
            )

    # Online-serving workload (any platform: the engine is single-chip by
    # design). Runs before the peak-pixel walk — the walk is expected to
    # eventually fail/eat budget and must not starve this measurement.
    if os.environ.get("BENCH_SERVING", "1") != "0":
        run_extra("serving_amoebanet3_32px", _measure_serving,
                  est_seconds=180.0)

    # Fleet recovery drill (router + 2 CPU replica subprocesses + kill
    # -9): rps-through-the-fault, requeue count, recovery latency.
    if os.environ.get("BENCH_FLEET", "1") != "0":
        run_extra("fleet_2replica", _measure_fleet, est_seconds=240.0)

    # Cold-start decomposition drill (telemetry/coldstart.py): a cold
    # respawn vs a warm-pool promotion, each recovery attributed across
    # spawn/import/construct/compile/warm/ready — bench-history trends
    # every phase_s series INVERTED so no single phase regrows silently.
    if os.environ.get("BENCH_COLDSTART", "1") != "0":
        run_extra("coldstart", _measure_coldstart, est_seconds=180.0)

    # Incident-engine drill: the kill drill scored by the incident
    # manager — MTTD/MTTR + first-cause blame accuracy. bench-history
    # trends incident.mttd_s / incident.mttr_s INVERTED (slower
    # detection or recovery is the regression; absent-not-zero).
    if os.environ.get("BENCH_INCIDENT", "1") != "0":
        run_extra("incident", _measure_incident, est_seconds=200.0)

    # Multi-tenant QoS (tenancy subsystem): noisy-neighbor victim p99
    # ratio + Jain's fairness index under a 10:1 flood, and the
    # tenancy-on overhead vs off — bench-history trends the ratio
    # INVERTED and fairness normal-sign.
    if os.environ.get("BENCH_MULTITENANT", "1") != "0":
        run_extra("multitenant", _measure_multitenant, est_seconds=150.0)

    # Numerics sentinel A/B + corrupt drill: canary-on vs -off rps and
    # the corruption→fence detection latency — bench-history trends
    # both INVERTED (a grown canary tax or slower detection regresses).
    if os.environ.get("BENCH_NUMERICS", "1") != "0":
        run_extra("numerics", _measure_numerics, est_seconds=120.0)

    # SP 2x2 halo/compute overlap A/B (CPU-mesh subprocess): both conv
    # impls' measured trace_overlap_ratio + step time in one round, so
    # bench-history can trend the overlap trajectory per arm.
    if os.environ.get("BENCH_SP_OVERLAP", "1") != "0":
        run_extra("sp2x2_overlap", _measure_sp_overlap, est_seconds=240.0)

    # Sharded-serving overlap A/B (CPU-mesh subprocess): the same two
    # conv impls on the SERVING hot path — a 2x2-sharded engine under
    # closed-loop load per arm, measured trace_overlap_ratio + p99
    # latency per arm trended by bench-history (latency inverted).
    if os.environ.get("BENCH_SERVING_SHARDED", "1") != "0":
        run_extra("serving_sharded", _measure_serving_sharded,
                  est_seconds=300.0)

    # Pipeline schedule A/B (CPU-mesh subprocess): gpipe vs interleaved
    # 1f1b, both arms' measured bubble fraction + img/s per round so
    # bench-history trends the bubble trajectory per schedule.
    if os.environ.get("BENCH_PIPELINE", "1") != "0":
        run_extra("pipeline", _measure_pipeline, est_seconds=180.0)

    # Gigapixel tiled inference (serve/tiled.py): peak feasible px walk
    # through the one-chip tile stream + latency at a fixed large size —
    # bench-history trends peak_px (normal) and p99 latency (inverted).
    if os.environ.get("BENCH_TILED", "1") != "0":
        run_extra("tiled_gigapixel", _measure_tiled_gigapixel,
                  est_seconds=240.0)

    if which in ("resnet", "all") and not on_cpu:
        def peak_px():
            # BASELINE.json capability metric: largest square resolution
            # whose full train step (fwd+bwd+update) fits ONE chip, bs=1 —
            # the single-chip floor of the "SP trains resolutions DP can't"
            # story (scripts/peak_pixels.py is the standalone walker).
            # Each size's success is recorded + emitted IMMEDIATELY: the
            # next (larger) attempt is expected to eventually fail, and a
            # wedged compile or budget kill must not erase a measured peak.
            entry = {
                "peak_trainable_px_per_chip": None,
                "img_per_sec_at_peak": None,
                "unit": "square image side, bs=1, one chip",
            }

            def record(size, ips, note=None, oom=None):
                if size is not None:
                    entry["peak_trainable_px_per_chip"] = size
                    entry["img_per_sec_at_peak"] = ips
                if note:
                    entry["stopped_by"] = note
                if oom is not None:
                    # Structured RESOURCE_EXHAUSTED parse (telemetry/
                    # memory.py) next to the raw stopped_by string: the
                    # wall's HBM table — used/limit/exceeded bytes and
                    # the largest buffers — lands in BENCH_*.json
                    # instead of dying in a truncated message.
                    entry["oom"] = oom
                extras["resnet_peak_pixels"] = entry
                _RESULT["extras"] = extras
                if _RESULT.get("metric"):
                    _emit()

            # Known-fatal sentinel: a failed walk attempt is a ~10-minute
            # compile the persistent cache can NOT memoize (failures are
            # never cached) — record it ourselves so every later bench run
            # skips straight past it. Entries carry the git revision and a
            # status: "confirmed" (the attempt genuinely raised) skips only
            # while the code is unchanged — any new commit invalidates the
            # verdict, so a round-N fix cannot be hidden by a round-(N-1)
            # cache entry (VERDICT r3 weak #6). "provisional" (attempt
            # started, never concluded — a driver kill mid-compile) is
            # retried once whenever the budget still allows a full attempt,
            # instead of requiring a manual BENCH_RETRY_FATAL=1 (ADVICE r3
            # medium). BENCH_RETRY_FATAL=1 still force-retries everything.
            sentinel = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                ".cache", "bench_known_fatal.json",
            )
            try:
                with open(sentinel) as f:
                    fatal = json.load(f)
            except Exception:  # noqa: BLE001 — absent/corrupt = empty
                fatal = {}

            prior = extras.get("resnet110_2048px_bs1", {})
            if prior.get("value") is not None:
                record(2048, prior["value"])
            for size in (3072, 4096, 8192):
                # 3072px: whole-model logarithmic recursion — under plain
                # "scan" the stored carries alone exceed HBM and the
                # remote-compile helper dies at buffer assignment; scanlog
                # is also 4x faster than scan2 at 3072 (0.165 vs 0.040
                # img/s, docs/PERF.md round 4). ≥4096px: straight to the
                # anchored-quadratic "scanq" tier (O(1) live boundaries
                # per run) — scanlog's ~23.7 GB live set is a confirmed
                # OOM there and its doomed compile costs ~10 uncacheable
                # minutes per attempt. BENCH_REMAT overrides.
                if remat_pref:
                    walk_remats = [remat_pref]
                elif size >= 4096:
                    walk_remats = ["scanq"]
                else:
                    walk_remats = ["scanlog", "scanq"]
                # Key covers everything that shapes the compiled program —
                # a different layout/dtype/policy A/B must not be skipped
                # on another config's verdict.
                from mpi4dl_tpu.train import scan_unroll

                # scanq program identity includes its store budget (set
                # below for the attempt; default 3000) — but only when
                # scanq is the policy that actually RUNS FIRST: at 3072
                # the walk is ["scanlog", "scanq"], and a scanlog
                # compile-fatal keyed to the scanq budget would be
                # spuriously invalidated by a later budget-default change,
                # re-paying scanlog's ~10-minute doomed compile (ADVICE r5).
                qtag = (
                    "_q" + os.environ.get("MPI4DL_TPU_SCANQ_STORE_MB", "3000")
                    if walk_remats[0] == "scanq" else ""
                )
                key = (
                    f"resnet110_{size}px_bs1_{'-'.join(walk_remats)}"
                    f"_{layout}_{jnp.dtype(dtype).name}_u{scan_unroll()}{qtag}"
                )
                skip = sentinel_skip_reason(
                    fatal.get(key), _git_rev(), _remaining(),
                    bool(os.environ.get("BENCH_RETRY_FATAL")),
                )
                if skip:
                    record(None, None, f"{size}: {skip}")
                    break
                if _remaining() < 150:
                    record(None, None, f"{size}: budget exhausted before attempt")
                    break
                cells = get_resnet_v2(
                    depth=get_depth(2, 12), num_classes=10,
                    pool_kernel=size // 4, layout=layout, dtype=dtype,
                )

                def write_sentinel():
                    try:
                        os.makedirs(os.path.dirname(sentinel), exist_ok=True)
                        with open(sentinel, "w") as f:
                            json.dump(fatal, f)
                    except Exception:  # noqa: BLE001 — sentinel is advisory
                        pass

                # Pre-mark the attempt as PROVISIONAL: a failed walk compile
                # takes ~10 uncacheable minutes, and a driver kill
                # mid-compile would otherwise erase the evidence. Success
                # REMOVES the marker; a genuine failure upgrades it to
                # "confirmed". A kill of a would-have-succeeded attempt
                # leaves only the provisional marker, which the next
                # sufficiently-budgeted run retries automatically.
                old = fatal.get(key)
                prior_tries = (
                    int(old.get("tries", 1))
                    if isinstance(old, dict)
                    and old.get("status") == "provisional"
                    and old.get("rev") == _git_rev()
                    else 0
                )
                fatal[key] = {
                    "status": "provisional",
                    "rev": _git_rev(),
                    "tries": prior_tries + 1,
                    "msg": "attempt started but never concluded — likely "
                    "killed mid-compile by the driver's budget",
                }
                write_sentinel()
                # scanq attempts carry the measured store-budget default:
                # 3000 MB grants the late small-carry runs the plain
                # stored scan (+67% at 4096: 0.0594 vs 0.0355 img/s,
                # docs/PERF.md round 5; 6000 MB OOMs). Env override wins.
                scanq_default = (
                    "scanq" in walk_remats
                    and "MPI4DL_TPU_SCANQ_STORE_MB" not in os.environ
                )
                if scanq_default:
                    os.environ["MPI4DL_TPU_SCANQ_STORE_MB"] = "3000"
                try:
                    ips, _, _ = _train_throughput(
                        cells, size, 1, 3, 1, dtype, walk_remats
                    )
                except Exception as e:  # noqa: BLE001 — walk stops here
                    msg = f"{type(e).__name__}: {str(e)[:120]}"
                    oom = None
                    from mpi4dl_tpu.telemetry import memory as memobs

                    if memobs.is_oom_error(e):
                        # OOM forensics: emit the schema-valid oom.report
                        # (counter + env-gated JSONL) and embed the parse
                        # in the result line, raw message kept alongside.
                        ev = memobs.emit_oom_report(
                            e, program=f"resnet110_{size}px_bs1_walk",
                            registry=_REGISTRY, events=_TELEMETRY_LOG,
                        )
                        oom = {
                            "parsed": ev["attrs"]["parsed"],
                            "largest_buffer": ev["attrs"]["largest_buffer"],
                        }
                    record(None, None, f"{size}: {msg}", oom=oom)
                    # Classify on the UNTRUNCATED text of the whole
                    # exception chain: wrapped transport errors can carry
                    # their signature past any prefix or in a __cause__.
                    if _is_transient_failure(e):
                        # Tunnel/helper transport flake ("response body
                        # closed", connection reset...): proves nothing
                        # about the program. Leave the marker PROVISIONAL
                        # (tries already bumped above) so the next run
                        # retries; two flakes in a row at one revision
                        # still stop the bleeding via the tries>=2 rule.
                        # Round-4 incident: a transient helper death
                        # confirmed-fataled the 3072px walk that had
                        # measured 0.165 img/s earlier the same day.
                        fatal[key]["msg"] = "transient: " + msg
                    else:
                        fatal[key] = {
                            "status": "confirmed", "rev": _git_rev(),
                            "msg": msg,
                        }
                    write_sentinel()
                    break
                finally:
                    if scanq_default:
                        os.environ.pop("MPI4DL_TPU_SCANQ_STORE_MB", None)
                fatal.pop(key, None)
                write_sentinel()
                record(size, round(ips, 3))
            return entry

        run_extra("resnet_peak_pixels", peak_px, est_seconds=150.0)

    if _RESULT.get("value") is None:
        # ADVICE r2: an all-failure run must say so explicitly, not hand
        # downstream consumers a null value under a success-shaped line.
        _RESULT.update(
            {
                "metric": _RESULT.get("metric") or f"bench_failed_{platform}",
                "value": None,
                "unit": "images/sec",
                "vs_baseline": None,
                "error": headline_error
                or "no configuration produced a throughput",
                "extras": extras,
            }
        )
        _emit()
        sys.exit(1)


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except Exception as _e:  # noqa: BLE001
        # ANY escape path must still leave one parseable line on stdout —
        # setup failures (device discovery, imports, env validation)
        # included; rc=1 with zero JSON is the round-1/2 failure shape
        # this file exists to eliminate.  If a real measurement already
        # landed, re-emit IT (annotated) as the final line so a
        # keep-last-line driver still records the value.
        if _RESULT.get("value") is not None:
            _RESULT["note"] = (
                f"late failure after measurement: "
                f"{type(_e).__name__}: {str(_e)[:200]}"
            )
            _emit()
            sys.exit(0)
        print(
            json.dumps(
                {
                    "metric": "bench_failed_setup",
                    "value": None,
                    "unit": "images/sec",
                    "vs_baseline": None,
                    "error": f"{type(_e).__name__}: {str(_e)[:300]}",
                }
            ),
            flush=True,
        )
        sys.exit(1)
