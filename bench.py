"""Headline benchmark: training throughput vs the reference's published numbers.

Headline metric (the JSON ``value``): ResNet-110(v2) @1024px bs=2, vs the
reference's best published ResNet@1024 number ~3.1 img/s (batch 2, spatial
parallelism, square slicing + halo-D2, multi-GPU MVAPICH2-GDR cluster; read
off ``docs/assets/images/ResNet_img_size_1024.png`` — BASELINE.md).

``extras`` carries the AmoebaNet-D (18 layers / 416 filters, the reference
benchmark defaults) numbers against ITS published charts — the reference's
headline model (BASELINE.json configs are AmoebaNet-centric):

- 1024px bs=2: ref best ≈3.0 img/s (AmeobaNet_img_size_1024.png)
- 2048px bs=2: ref best ≈5.1 img/s (AmeobaNet_img_size_2048.png)

Every entry also reports MFU (model-FLOPs utilization, analytic conv+dot
count — see mpi4dl_tpu/flops.py); the north star is ≥45% (BASELINE.json).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N,
     "mfu": ..., "extras": {...}}
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

RESNET_BASELINE = 3.1  # img/s, ResNet@1024 bs2, best SP config (BASELINE.md)
AMOEBA_BASELINE = {  # img/s (BASELINE.md chart reads)
    (1024, 2): 3.0,
    (2048, 2): 5.1,
    (2048, 1): 2.9,
}


def _train_throughput(cells, image_size, batch, steps, warmup, dtype, remats):
    """img/s for a Trainer over the cell list; tries remat policies in
    order, falling back on genuine OOM only (VERDICT weak #1 lesson)."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.train import Trainer

    cfg = ParallelConfig(
        batch_size=batch, split_size=1, spatial_size=0, image_size=image_size
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((batch, image_size, image_size, 3)), dtype
    )
    y = jnp.asarray(rng.integers(0, 10, size=(batch,)), jnp.int32)

    state = trainer = None
    for remat in remats:
        try:
            trainer = Trainer(cells, num_spatial_cells=0, config=cfg, remat=remat)
            xs, ys = trainer.shard_batch(x, y)
            state = trainer.init(jax.random.PRNGKey(0), x.shape, dtype=dtype)
            for _ in range(warmup):
                state, metrics = trainer.train_step(state, xs, ys)
            # A device-to-host READ (not just block_until_ready) is the only
            # portable way to force the dispatched chain to fully execute on
            # every backend — tunneled/virtualized TPU runtimes have been
            # observed to report readiness without having run dependent
            # steps, inflating throughput ~400x. The final loss value
            # transitively depends on every step in the chain, so one scalar
            # read times the real work.
            float(metrics["loss"])
            break
        except jax.errors.JaxRuntimeError as e:
            # Only genuine memory exhaustion justifies retrying with a
            # leaner remat policy; anything else (e.g. a kernel compile
            # failure) must surface immediately, not after a doubled
            # time-to-failure (ADVICE.md round-1 low finding).
            msg = str(e)
            is_oom = "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
            if not is_oom or remat == remats[-1]:
                raise
            print(f"# remat={remat} OOM; retrying leaner", flush=True)
            state = trainer = None

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.train_step(state, xs, ys)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    return batch * steps / dt, trainer.remat


def main():
    from mpi4dl_tpu.utils import apply_platform_env

    apply_platform_env()  # honor JAX_PLATFORMS even under the axon plugin

    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.flops import mfu, train_flops_per_image
    from mpi4dl_tpu.models.amoebanet import amoebanetd
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.utils import get_depth

    platform = jax.devices()[0].platform
    on_cpu = platform == "cpu"
    image_size = int(os.environ.get("BENCH_IMAGE_SIZE", "1024"))
    batch = int(os.environ.get("BENCH_BATCH", "2"))
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    which = os.environ.get("BENCH_MODEL", "all")
    if which not in ("resnet", "amoebanet", "all"):
        raise ValueError(f"BENCH_MODEL must be resnet|amoebanet|all, got {which!r}")
    warmup = 2
    if on_cpu and "BENCH_IMAGE_SIZE" not in os.environ:
        image_size, steps = 128, 3  # keep the CPU smoke path tractable

    dtype = jnp.float32 if on_cpu else jnp.bfloat16
    # "scan" remat: ResNet-110 @1024px stores ~64G of activations with no
    # remat — far beyond one chip's HBM — and the scan policy (one compiled
    # body per repeated stage, compact un-padded residuals, scheduling
    # barriers) trains 2.4x faster than per-cell jax.checkpoint on top of
    # fitting (see Trainer.__init__ docstring for measurements).
    # "scan_save" additionally keeps conv outputs (~2 bytes/pixel-channel)
    # to skip the backward's forward-recompute; it fits up to ~2M pixels
    # per example on one chip — try it first, fall back to "scan" on OOM.
    remat_pref = os.environ.get("BENCH_REMAT")
    # ResNet: cell_save first (fastest, most memory), leaner scan policies
    # on OOM (2048px+). AmoebaNet: scan_save first — compiling its 24 big
    # per-cell graphs (cell_save) crashes the bench runtime's compile
    # helper outright, while the scanned form (3 stacked normal-cell
    # bodies) compiles fine and measured 4.72 img/s @1024.
    remats = [remat_pref] if remat_pref else ["cell_save", "scan_save", "scan"]
    amoeba_remats = [remat_pref] if remat_pref else ["scan_save", "scan"]

    result = {}
    extras = {}

    if which in ("resnet", "all"):
        depth = get_depth(2, 12)  # 110 — the reference benchmark's ResNet
        # Packed activation layout (ops/packed.py): measured win on TPU;
        # BENCH_LAYOUT=nhwc reverts to the stock layout for A/B.
        layout = os.environ.get(
            "BENCH_LAYOUT", "packed" if not on_cpu else "nhwc"
        )
        cells = get_resnet_v2(
            depth=depth, num_classes=10, pool_kernel=image_size // 4,
            layout=layout, dtype=dtype,
        )
        ips, remat = _train_throughput(
            cells, image_size, batch, steps, warmup, dtype, remats
        )
        # MFU counts the LOGICAL model's FLOPs (stock layout) — the packed
        # layout executes more device FLOPs by design and must not flatter
        # the utilization number.
        logical = get_resnet_v2(
            depth=depth, num_classes=10, pool_kernel=image_size // 4, dtype=dtype
        )
        util = mfu(
            ips,
            train_flops_per_image(logical, image_size, dtype),
            n_devices=jax.device_count(),
        )
        result = {
            "metric": f"resnet110_{image_size}px_bs{batch}_train_{platform}",
            "value": round(ips, 3),
            "unit": "images/sec",
            "vs_baseline": round(ips / RESNET_BASELINE, 3),
            "remat": remat,
            "mfu": round(util, 4) if util is not None else None,
        }

    if which in ("resnet", "all") and os.environ.get("BENCH_RESNET_2048"):
        # Optional high-res point (BASELINE.md: ref ResNet@2048 SP best
        # ~1.0 img/s bs=1, bs=2 OOMs every published scheme).
        cells = get_resnet_v2(
            depth=get_depth(2, 12), num_classes=10, pool_kernel=512,
            layout="packed" if not on_cpu else "nhwc", dtype=dtype,
        )
        try:
            ips, remat = _train_throughput(
                cells, 2048, 1, steps, warmup, dtype, remats
            )
            extras["resnet110_2048px_bs1"] = {
                "value": round(ips, 3),
                "remat": remat,
                "vs_baseline": round(ips / 1.0, 3),
            }
        except Exception as e:  # noqa: BLE001
            extras["resnet110_2048px_bs1"] = {
                "error": f"{type(e).__name__}: {str(e)[:200]}"
            }

    if which in ("amoebanet", "all"):
        # (2048, 2) is recorded as an error today: its program crashes the
        # bench runtime's compile helper under every remat policy; (2048, 1)
        # compiles and runs (the reference's own bs-2 ResNet@2048 OOMs on
        # all published schemes, BASELINE.md).
        amoeba_cfgs = (
            [(1024, 2), (2048, 2), (2048, 1)] if not on_cpu else [(64, 2)]
        )
        layers, filters = (18, 416) if not on_cpu else (6, 64)
        for size, b in amoeba_cfgs:
            cells = amoebanetd(
                num_classes=10, num_layers=layers, num_filters=filters,
                dtype=dtype,
            )
            tag = f"amoebanetd_{size}px_bs{b}"
            try:
                ips, remat = _train_throughput(
                    cells, size, b, steps, warmup, dtype, amoeba_remats
                )
            except Exception as e:  # noqa: BLE001 — extras never kill the line
                extras[tag] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
                continue
            util = mfu(
                ips,
                train_flops_per_image(cells, size, dtype),
                n_devices=jax.device_count(),
            )
            entry = {
                "value": round(ips, 3),
                "remat": remat,
                "mfu": round(util, 4) if util is not None else None,
            }
            base = AMOEBA_BASELINE.get((size, b))
            if base:
                entry["vs_baseline"] = round(ips / base, 3)
            extras[tag] = entry

    if not result:  # amoebanet-only run: promote a SUCCESSFUL extra
        ok = {t: e for t, e in extras.items() if "value" in e} or extras
        tag, entry = next(iter(ok.items()))
        result = {
            "metric": f"{tag}_train_{platform}",
            "value": entry.get("value"),
            "unit": "images/sec",
            "vs_baseline": entry.get("vs_baseline"),
        }
    if extras:
        result["extras"] = extras
    print(json.dumps(result))


if __name__ == "__main__":
    main()
